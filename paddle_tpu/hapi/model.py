"""High-level Model API (parity: python/paddle/hapi/model.py —
Model.fit/evaluate/predict/save/load with prepare(optimizer, loss, metrics)).

TPU-first: ``fit`` trains through one compiled ``jit.TrainStep`` (forward +
backward + update as a single XLA computation) instead of the reference's
per-op dygraph loop; ``evaluate``/``predict`` run a compiled ``EvalStep``.
The callback protocol (hapi/callbacks.py parity) fires around the compiled
steps. ``batch_size`` is honored by wrapping map-style datasets in a
DataLoader.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor
from ..framework.io import load as _load
from ..framework.io import save as _save
from .callbacks import config_callbacks


def _np(o):
    return o.numpy() if isinstance(o, Tensor) else np.asarray(o)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._amp_level = None
        self._train_step = None
        self._eval_step = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else ([metrics] if metrics else [])
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        self._train_step = None  # invalidate any compiled step
        self._eval_step = None
        return self

    # -- compiled steps ----------------------------------------------------
    def _loss_adapter(self):
        loss = self._loss

        def fn(outputs, *labels):
            outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
            return loss(*outs, *labels)

        return fn

    def _get_train_step(self):
        if self._train_step is None:
            from ..jit import TrainStep

            self._train_step = TrainStep(
                self.network, self._optimizer, self._loss_adapter(),
                amp_level=self._amp_level, return_outputs=bool(self._metrics))
        return self._train_step

    def _get_eval_step(self):
        if self._eval_step is None:
            from ..jit import EvalStep

            if self._train_step is not None:
                self._train_step.sync_to_model()
            self._eval_step = EvalStep(self.network)
        return self._eval_step

    # -- single-batch eager APIs (reference parity) ------------------------
    def train_batch(self, inputs, labels=None):
        self.network.train()
        outputs = self.network(*_as_list(inputs))
        losses = self._loss_adapter()(outputs, *_as_list(labels))
        losses.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        self._train_step = None  # eager updates invalidate the compiled state
        return losses.numpy()

    def eval_batch(self, inputs, labels=None):
        from ..framework.autograd import no_grad

        self.network.eval()
        inputs = _as_list(inputs)
        with no_grad():
            outputs = self.network(*inputs)
            losses = self._loss_adapter()(outputs, *_as_list(labels))
        return losses.numpy(), outputs

    def predict_batch(self, inputs):
        from ..framework.autograd import no_grad

        self.network.eval()
        with no_grad():
            return self.network(*_as_list(inputs))

    # -- data plumbing -----------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle=False, drop_last=False, num_workers=0):
        if data is None:
            return None
        if hasattr(data, "__getitem__") and not hasattr(data, "batch_size") and not isinstance(data, (list, tuple)):
            from ..io import DataLoader

            return DataLoader(data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last, num_workers=num_workers)
        return data  # already an iterable of batches (DataLoader, generator…)

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[0], list(batch[1:])
        return batch, []

    # -- main loops --------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1, log_freq=10, save_dir=None, save_freq=1, callbacks=None, verbose=1, shuffle=True, drop_last=False, num_workers=0):
        loader = self._to_loader(train_data, batch_size, shuffle, drop_last, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs, steps=steps, log_freq=log_freq, verbose=verbose, metrics=[m.name() for m in self._metrics])
        if save_dir is not None:
            from .callbacks import ModelCheckpoint

            cbks.callbacks.append(ModelCheckpoint(save_freq, save_dir))
            cbks.callbacks[-1].set_model(self)
            cbks.callbacks[-1].set_params({})
        step_fn = self._get_train_step()
        self.network.train()
        self.stop_training = False
        history = []
        cbks.on_train_begin()
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            losses = []
            for i, batch in enumerate(loader):
                cbks.on_train_batch_begin(i)
                x, ys = self._split_batch(batch)
                metrics = step_fn(_as_list(x), ys)
                logs = {"loss": float(metrics["loss"]), "lr": float(metrics["lr"])}
                losses.append(logs["loss"])
                if self._metrics and "outputs" in metrics:
                    outs = metrics["outputs"]
                    for m in self._metrics:
                        m.update(*m.compute(outs, *ys))
                        logs[m.name()] = m.accumulate()
                cbks.on_train_batch_end(i, logs)
            epoch_logs = {"loss": float(np.mean(losses)) if losses else 0.0}
            for m in self._metrics:
                epoch_logs[m.name()] = m.accumulate()
            history.append(epoch_logs["loss"])
            cbks.on_epoch_end(epoch, epoch_logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size=batch_size, verbose=0, num_workers=num_workers)
                cbks.on_eval_end(eval_logs)
            if self.stop_training:
                break
        step_fn.sync_to_model()  # expose trained weights to save()/eager use
        self._eval_step = None
        cbks.on_train_end({"loss": history[-1] if history else 0.0})
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=1, num_workers=0, callbacks=None):
        loader = self._to_loader(eval_data, batch_size, num_workers=num_workers)
        cbks = config_callbacks(callbacks, model=self, steps=len(loader) if hasattr(loader, "__len__") else None, log_freq=log_freq, verbose=verbose)
        if self._train_step is not None:
            self._train_step.sync_to_model()
        eval_step = self._get_eval_step()
        self.network.eval()
        for m in self._metrics:
            m.reset()
        losses = []
        cbks.on_eval_begin()
        for i, batch in enumerate(loader):
            cbks.on_eval_batch_begin(i)
            x, ys = self._split_batch(batch)
            outputs = eval_step(*_as_list(x))
            loss = self._loss_adapter()(outputs, *ys) if self._loss is not None else None
            if loss is not None:
                losses.append(float(loss))
            for m in self._metrics:
                m.update(*m.compute(outputs, *ys))
            cbks.on_eval_batch_end(i, {"loss": losses[-1] if losses else 0.0})
        result = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        cbks.on_eval_end(result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, num_workers=num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=0)
        if self._train_step is not None:
            self._train_step.sync_to_model()
        eval_step = self._get_eval_step()
        self.network.eval()
        outs = []
        cbks.on_predict_begin()
        for i, batch in enumerate(loader):
            cbks.on_predict_batch_begin(i)
            x, _ = self._split_batch(batch)
            outs.append(eval_step(*_as_list(x)))
            cbks.on_predict_batch_end(i)
        cbks.on_predict_end()
        if stack_outputs:
            # multi-output networks: concatenate per output field (reference
            # hapi stacks each fetch separately)
            if outs and isinstance(outs[0], (tuple, list)):
                n_fields = len(outs[0])
                return [
                    np.concatenate([_np(o[j]) for o in outs], axis=0)
                    for j in range(n_fields)
                ]
            return [np.concatenate([_np(o) for o in outs], axis=0)]
        return outs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        if self._train_step is not None:
            self._train_step.sync_to_model()
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and hasattr(self._optimizer, "state_dict"):
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os

        state = _load(path + ".pdparams") if not path.endswith(".pdparams") else _load(path)
        self.network.set_state_dict(state)
        self._train_step = None
        self._eval_step = None
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        n_params = sum(p.size for p in self.network.parameters())
        print(f"{type(self.network).__name__}: {n_params:,} parameters")
        return {"total_params": n_params}
