"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface (see SURVEY.md), built on JAX/XLA/pjit/Pallas.

Public API layout mirrors paddle's: ``paddle_tpu.nn``, ``paddle_tpu.tensor``
(flattened into the root namespace like ``paddle.*``), ``paddle_tpu.optimizer``,
``paddle_tpu.distributed`` (fleet), ``paddle_tpu.amp``, ``paddle_tpu.io``,
``paddle_tpu.jit``, ``paddle_tpu.static``.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (
    Tensor,
    backward,
    convert_dtype,
    enable_grad,
    get_default_dtype,
    get_device,
    get_flags,
    is_compiled_with_tpu,
    is_grad_enabled,
    no_grad,
    seed,
    set_default_dtype,
    set_device,
    set_flags,
    set_grad_enabled,
)
from .tensor import *  # noqa: F401,F403 — paddle flattens tensor ops into root
from .tensor import to_tensor  # noqa: F401

from . import tensor  # noqa: F401

# subpackages are imported lazily-ish at the bottom so circular deps stay sane
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from .static import disable_static, enable_static, in_dynamic_mode  # noqa: E402
from .framework.io import load, save  # noqa: E402
from .hapi.model import Model  # noqa: E402
from .nn.layer.container import ParameterList  # noqa: E402


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False, allow_unused=False):
    """paddle.grad parity (eager): returns grads of outputs w.r.t. inputs."""
    from .framework import autograd as _ag

    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    for t in inputs:
        t.grad = None
    _ag.backward(outputs, grad_outputs, retain_graph=retain_graph)
    grads = []
    for t in inputs:
        if t.grad is None and not allow_unused:
            raise ValueError("input tensor unused in graph; pass allow_unused=True")
        grads.append(t.grad)
        t.grad = None
    return grads


def ones_like_(x):  # pragma: no cover - paddle private compat
    from .tensor.creation import ones_like

    return ones_like(x)


def device_count() -> int:
    import jax

    return jax.device_count()
