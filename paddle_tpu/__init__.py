"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface (see SURVEY.md), built on JAX/XLA/pjit/Pallas.

Public API layout mirrors paddle's: ``paddle_tpu.nn``, ``paddle_tpu.tensor``
(flattened into the root namespace like ``paddle.*``), ``paddle_tpu.optimizer``,
``paddle_tpu.distributed`` (fleet), ``paddle_tpu.amp``, ``paddle_tpu.io``,
``paddle_tpu.jit``, ``paddle_tpu.static``.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (
    Tensor,
    backward,
    convert_dtype,
    enable_grad,
    get_default_dtype,
    get_device,
    get_flags,
    is_compiled_with_tpu,
    is_grad_enabled,
    no_grad,
    seed,
    set_default_dtype,
    set_device,
    set_flags,
    set_grad_enabled,
)
from .tensor import *  # noqa: F401,F403 — paddle flattens tensor ops into root
from .tensor import to_tensor  # noqa: F401

from . import tensor  # noqa: F401

# subpackages are imported lazily-ish at the bottom so circular deps stay sane
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import fft  # noqa: F401,E402
from . import signal  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import hub  # noqa: F401,E402
from . import callbacks  # noqa: F401,E402
from . import analysis  # noqa: F401,E402
from . import observability  # noqa: F401,E402
from . import stability  # noqa: F401,E402
from .static import disable_static, enable_static, in_dynamic_mode  # noqa: E402
from .framework.io import load, save  # noqa: E402
from .hapi.model import Model  # noqa: E402
from .hapi.dynamic_flops import flops  # noqa: E402
from .nn.layer.container import ParameterList  # noqa: E402
from .framework.param_attr import (  # noqa: E402
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    NPUPlace,
    ParamAttr,
    TPUPlace,
)
from .distributed import DataParallel  # noqa: E402

# dtype aliases (reference: paddle.float32 etc. are framework dtypes; here
# the framework dtype IS the numpy/jax dtype object)
import numpy as _np  # noqa: E402

dtype = _np.dtype
bool = _np.dtype("bool")  # noqa: A001 — paddle exports these exact names
uint8 = _np.dtype("uint8")
int8 = _np.dtype("int8")
int16 = _np.dtype("int16")
int32 = _np.dtype("int32")
int64 = _np.dtype("int64")
float16 = _np.dtype("float16")
float32 = _np.dtype("float32")
float64 = _np.dtype("float64")
complex64 = _np.dtype("complex64")
complex128 = _np.dtype("complex128")
import jax.numpy as _jnp  # noqa: E402

bfloat16 = _jnp.bfloat16


def is_floating_point(x):
    import jax.numpy as jnp

    return jnp.issubdtype(tensor._helpers.ensure_tensor(x)._value.dtype, jnp.floating)


def is_integer(x):
    import jax.numpy as jnp

    return jnp.issubdtype(tensor._helpers.ensure_tensor(x)._value.dtype, jnp.integer)


def is_complex(x):
    import jax.numpy as jnp

    return jnp.issubdtype(tensor._helpers.ensure_tensor(x)._value.dtype, jnp.complexfloating)


def set_printoptions(precision=None, threshold=None, edgeitems=None, sci_mode=None, linewidth=None):
    """numpy printoptions passthrough (reference paddle.set_printoptions)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def batch(reader, batch_size, drop_last=False):
    """Batch a sample generator (reference paddle.batch / fluid batch.py)."""

    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return gen


def summary(net, input_size=None, dtypes=None):
    """paddle.summary parity: wraps hapi Model.summary for a bare Layer."""
    return Model(net).summary(input_size)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    """Standalone parameter (reference paddle.create_parameter /
    fluid.layers.create_parameter)."""
    from .nn.layer.base import Layer

    holder = Layer()
    holder._dtype = str(dtype)
    p = holder.create_parameter(list(shape), dtype=str(dtype), attr=attr, is_bias=is_bias,
                                default_initializer=default_initializer)
    if name:
        p.name = name
    return p


def check_shape(shape):
    """Validate a shape argument (reference fluid/layers/utils.py:378)."""
    if isinstance(shape, Tensor):
        return
    for d in shape:
        if not isinstance(d, (int, _np.integer)) and not isinstance(d, Tensor):
            raise TypeError(f"shape entries must be ints or Tensors, got {type(d).__name__}")
        if isinstance(d, (int, _np.integer)) and d < -1:
            raise ValueError(f"shape dims must be >= -1, got {d}")


def disable_signal_handler():
    """No-op (reference disables its C++ signal interceptors; this runtime
    installs none)."""


def get_cuda_rng_state():
    """Accelerator RNG state (maps to the framework RNG on TPU)."""
    from .framework.random import get_rng_state

    return get_rng_state()


def set_cuda_rng_state(state):
    from .framework.random import set_rng_state

    set_rng_state(state)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False, allow_unused=False):
    """paddle.grad parity (eager): returns grads of outputs w.r.t. inputs."""
    from .framework import autograd as _ag

    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    for t in inputs:
        t.grad = None
    _ag.backward(outputs, grad_outputs, retain_graph=retain_graph)
    grads = []
    for t in inputs:
        if t.grad is None and not allow_unused:
            raise ValueError("input tensor unused in graph; pass allow_unused=True")
        grads.append(t.grad)
        t.grad = None
    return grads


def ones_like_(x):  # pragma: no cover - paddle private compat
    from .tensor.creation import ones_like

    return ones_like(x)


def device_count() -> int:
    import jax

    return jax.device_count()
from . import regularizer  # noqa: F401,E402
