"""paddle_tpu.incubate.nn — fused transformer layers.

Parity: reference python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention:136, FusedFeedForward:327,
FusedTransformerEncoderLayer:462 — thin wrappers over the fused CUDA ops
fused_attention_op.cu / fused_feedforward_op.cu). TPU-first: "fusion" is the
Pallas flash-attention kernel plus XLA's own elementwise fusion, so these
layers are numerically the unfused ones with the fast attention path pinned
on.
"""
from __future__ import annotations

import paddle_tpu.nn.functional as F
from ...nn.initializer import Constant, XavierUniform
from ...nn.layer.base import Layer
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.norm import LayerNorm
from ...tensor import manipulation as M

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN attention block: LN -> qkv proj -> flash attention ->
    out proj -> dropout -> residual (fused_transformer.py:136 semantics,
    including the residual add — the reference op fuses the whole block)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide num_heads")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim, weight_attr=qkv_weight_attr, bias_attr=qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr=linear_weight_attr, bias_attr=linear_bias_attr)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        x = self.norm(query) if self.normalize_before else query
        b, s = x.shape[0], x.shape[1]
        qkv = M.reshape(self.qkv_proj(x), [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (M.squeeze(t, 2) for t in M.split(qkv, 3, axis=2))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate, training=self.training)
        out = self.out_proj(M.reshape(out, [b, s, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    """LN -> linear -> act -> dropout -> linear -> dropout -> residual
    (fused_transformer.py:327)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr=linear1_weight_attr, bias_attr=linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr=linear2_weight_attr, bias_attr=linear2_bias_attr)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout1 = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.dropout2 = Dropout(dropout_rate)

    def forward(self, src, cache=None):
        residual = src
        x = self.norm(src) if self.normalize_before else src
        x = self.dropout1(getattr(F, self.activation)(self.linear1(x)))
        x = self.dropout2(self.linear2(x))
        out = residual + x
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """FusedMultiHeadAttention + FusedFeedForward (fused_transformer.py:462)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward,
            dropout_rate=dropout_rate,
            act_dropout_rate=act_dropout_rate if act_dropout_rate is not None else dropout_rate,
            activation=activation, normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
