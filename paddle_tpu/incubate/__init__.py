"""paddle_tpu.incubate — incubating APIs (parity: python/paddle/incubate).

- ``incubate.nn``: fused transformer layers (Pallas-flash backed)
- ``incubate.optimizer``: LookAhead, ModelAverage
- ``incubate.autotune``: kernel/dataloader autotune config (reference
  python/paddle/incubate/autotune.py — on TPU, XLA autotunes; the knobs are
  recorded and the flash-attention toggle is honored)
- ``incubate.distributed``: MoE re-export (reference
  incubate/distributed/models/moe)
- ``incubate.asp``: n:m structured sparsity (fluid/contrib/sparsity parity)
- graph ops: graph_send_recv / graph_reindex / fused softmax-mask
  (incubate/operators parity; segment_* reductions under XLA)
"""
from __future__ import annotations

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401 — n:m structured sparsity (contrib/sparsity parity)
from .graph_ops import (  # noqa: F401
    graph_reindex,
    graph_send_recv,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)

from ..autograd import functional as autograd  # noqa: F401 — jacobian/hessian (incubate.autograd parity)


class _MoENamespace:
    @property
    def MoELayer(self):
        from ..distributed.moe import MoELayer

        return MoELayer


class _DistributedModels:
    moe = _MoENamespace()


class _Distributed:
    models = _DistributedModels()


distributed = _Distributed()

_autotune_config = {"kernel": {"enable": True}, "dataloader": {"enable": False}, "layout": {"enable": False}}


def autotune_config():
    return dict(_autotune_config)


class autotune:
    """Kernel autotuning (reference python/paddle/incubate/autotune.py
    set_config + phi/kernels/autotune/ AlgorithmsCache). On TPU, XLA
    autotunes its own fusions; what remains tunable are OUR Pallas kernel
    block sizes. ``tune_flash_blocks`` times candidate (block_q, block_k_fwd,
    block_k_bwd) configs for a given attention shape on the live backend,
    applies the winner via flash_attention_flat.set_blocks, and persists it
    (AlgorithmsCache parity) keyed by device kind + shape; ``load_tuned``
    re-applies a cached winner in a fresh process."""

    CACHE = ".autotune_cache.json"

    @staticmethod
    def set_config(config=None):
        from ..framework.flags import set_flags

        if not config:
            return
        _autotune_config.update(config)
        kern = config.get("kernel", {})
        if "enable" in kern:
            set_flags({"FLAGS_use_flash_attention": bool(kern["enable"])})

    @staticmethod
    def _cache_path(path=None):
        import os

        if path:
            return path
        env = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE")
        if env:
            return env
        return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu", "autotune.json")

    @staticmethod
    def _cache_key(shape):
        import jax

        d0 = jax.devices()[0]
        kind = getattr(d0, "device_kind", None) or d0.platform
        return f"{kind}/b{shape[0]}s{shape[1]}h{shape[2]}d{shape[3]}"

    @staticmethod
    def tune_flash_blocks(shape=(8, 1024, 16, 64), iters=10, cache_path=None,
                          candidates=None, on_result=None, on_error=None, _timer=None):
        """Sweep block configs for the flat flash kernels on ``shape``
        (b, s, h, d); apply + persist the fastest. ``on_result(blocks, dt)``
        fires per successful candidate, ``on_error(blocks, exc)`` per failed
        one (compile blowups stay visible). Returns the winning (block_q,
        block_k_fwd, block_k_bwd) or None when the kernels are unavailable
        on this backend (CPU test meshes)."""
        import time

        from ..ops import flash_attention_flat as ff

        b, s, h, d = shape
        # packed=False: the superset gate (full-dim head groups are legal
        # unpacked); block sizes are shared globals, so tuning the unpacked
        # path tunes the packed dispatch too
        if _timer is None and not ff.enabled((b, s, 3, h, d), packed=False):
            return None
        cands = candidates or [(bq, bkf, bkb)
                               for bq in (256, 512) for bkf in (512, 1024)
                               for bkb in (128, 256)]

        def default_timer(blocks):
            import jax
            import jax.numpy as jnp
            import numpy as np

            rng = np.random.default_rng(0)
            q, k, v, g = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
                          for _ in range(4))
            f = jax.jit(jax.value_and_grad(
                lambda q, k, v, g: jnp.sum(ff.flash_flat(q, k, v, True).astype(jnp.float32)
                                           * g.astype(jnp.float32)), argnums=(0, 1, 2)))
            jax.block_until_ready(f(q, k, v, g))
            t0 = time.perf_counter()
            for _ in range(iters):
                out = f(q, k, v, g)
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / iters

        timer = _timer or default_timer
        prior = ff.set_blocks()  # read current (no-op set)
        best, best_t = None, float("inf")
        seen = set()
        for blocks in cands:
            eff = (min(blocks[0], s), min(blocks[1], s), min(blocks[2], s))
            if any(s % e for e in eff) or eff in seen:
                continue  # indivisible, or clamps to an already-timed config
            seen.add(eff)
            ff.set_blocks(*blocks)
            try:
                dt = timer(blocks)
            except Exception as exc:
                if on_error is not None:
                    on_error(blocks, exc)
                continue
            if on_result is not None:
                on_result(blocks, dt)
            if dt < best_t:
                best, best_t = blocks, dt
        if best is None:
            ff.set_blocks(*prior)
            return None
        ff.set_blocks(*best)
        autotune.save_tuned(shape, best, cache_path)
        return tuple(best)

    @staticmethod
    def save_tuned(shape, blocks, cache_path=None):
        import json
        import os

        path = autotune._cache_path(cache_path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            cache = json.load(open(path))
        except Exception:
            cache = {}
        cache[autotune._cache_key(shape)] = list(blocks)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:  # atomic replace: concurrent writers
            json.dump(cache, f)    # cannot interleave/corrupt the cache
        os.replace(tmp, path)

    @staticmethod
    def load_tuned(shape=(8, 1024, 16, 64), cache_path=None):
        """Apply a previously tuned config for ``shape``; True if found."""
        import json

        from ..ops import flash_attention_flat as ff

        try:
            cache = json.load(open(autotune._cache_path(cache_path)))
        except Exception:
            return False
        best = cache.get(autotune._cache_key(shape))
        if not best:
            return False
        ff.set_blocks(*best)
        return True


class _PrimState:
    """incubate.autograd prim-op switches (reference
    python/paddle/incubate/autograd/primx.py + enable_prim). Under JAX every
    op already lowers to differentiable primitives and composes with
    forward-/reverse-mode (jvp/vjp/jacobian/hessian in
    paddle_tpu.autograd.functional), so the switch records intent only."""

    enabled = False


def enable_prim():
    _PrimState.enabled = True


def disable_prim():
    _PrimState.enabled = False


def prim_enabled():
    return _PrimState.enabled


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode AD (reference incubate/autograd/primapi.py forward_grad):
    jvp of the graph from ``inputs`` to ``outputs``."""
    from ..autograd import jvp as _jvp

    raise NotImplementedError(
        "use paddle_tpu.autograd.jvp(func, xs, v) — forward-mode requires "
        "the function form (JAX traces functions, not taped graphs)")


# top-level incubate re-exports (reference incubate/__init__.py __all__)
from .optimizer import LookAhead, ModelAverage  # noqa: E402,F401


def _segment_reduce(kind):
    def f(data, segment_ids, name=None):
        import jax
        import jax.numpy as jnp

        from ..tensor._helpers import ensure_tensor, op

        d, s = ensure_tensor(data), ensure_tensor(segment_ids)
        n = int(jnp.max(s._value)) + 1 if s._value.size else 0

        def fn(dv, sv):
            if kind == "mean":
                tot = jax.ops.segment_sum(dv, sv, num_segments=n)
                cnt = jax.ops.segment_sum(jnp.ones_like(sv, dv.dtype), sv, num_segments=n)
                return tot / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (dv.ndim - 1))
            r = getattr(jax.ops, f"segment_{kind}")(dv, sv, num_segments=n)
            if kind in ("max", "min"):
                # empty segments come back ±inf; reference fills 0
                r = jnp.where(jnp.isfinite(r), r, 0)
            return r

        return op(fn, d, s, _name=f"segment_{kind}")

    f.__name__ = f"segment_{kind}"
    return f


segment_sum = _segment_reduce("sum")
segment_mean = _segment_reduce("mean")
segment_max = _segment_reduce("max")
segment_min = _segment_reduce("min")


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, return_eids=False, name=None):
    """K-hop neighbor sampling over a CSC graph (reference
    incubate/operators/graph_khop_sampler). Host-side (data-dependent
    output sizes), like the reference's CPU sampling path."""
    import numpy as np

    from ..framework.core import _wrap_value
    from ..tensor._helpers import ensure_tensor, unwrap
    import jax.numpy as jnp

    rows = np.asarray(unwrap(ensure_tensor(row)))
    cp = np.asarray(unwrap(ensure_tensor(colptr)))
    nodes = np.asarray(unwrap(ensure_tensor(input_nodes))).ravel()
    rng = np.random.default_rng()
    edge_src, edge_dst, layers = [], [], [nodes]
    frontier = nodes
    for k in sample_sizes:
        nxt = []
        for v in frontier:
            nbrs = rows[cp[v]:cp[v + 1]]
            if len(nbrs) > k:
                nbrs = rng.choice(nbrs, size=k, replace=False)
            for u in nbrs:
                edge_src.append(u)
                edge_dst.append(v)
            nxt.extend(nbrs.tolist())
        frontier = np.unique(np.asarray(nxt, np.int64)) if nxt else np.asarray([], np.int64)
        layers.append(frontier)
    uniq = np.unique(np.concatenate([l for l in layers if len(l)])) if any(len(l) for l in layers) else np.asarray([], np.int64)
    remap = {int(v): i for i, v in enumerate(uniq)}
    src = np.asarray([remap[int(u)] for u in edge_src], np.int64)
    dst = np.asarray([remap[int(v)] for v in edge_dst], np.int64)
    return (_wrap_value(jnp.asarray(src)), _wrap_value(jnp.asarray(dst)),
            _wrap_value(jnp.asarray(uniq)),
            _wrap_value(jnp.asarray(np.arange(len(src), dtype=np.int64))))


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                           return_eids=False, perm_buffer=None, name=None):
    """One-hop neighbor sampling (reference graph_sample_neighbors op).
    Host-side. Returns (out_neighbors, out_count [, out_eids])."""
    import numpy as np

    from ..framework.core import _wrap_value
    from ..tensor._helpers import ensure_tensor, unwrap
    import jax.numpy as jnp

    rows = np.asarray(unwrap(ensure_tensor(row)))
    cp = np.asarray(unwrap(ensure_tensor(colptr)))
    nodes = np.asarray(unwrap(ensure_tensor(input_nodes))).ravel()
    ev = np.asarray(unwrap(ensure_tensor(eids))) if eids is not None else None
    rng = np.random.default_rng()
    out, counts, out_eids = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        idx = np.arange(lo, hi)
        if 0 <= sample_size < len(idx):
            idx = rng.choice(idx, size=sample_size, replace=False)
        out.extend(rows[idx].tolist())
        counts.append(len(idx))
        if return_eids:
            out_eids.extend((ev[idx] if ev is not None else idx).tolist())
    res = (_wrap_value(jnp.asarray(np.asarray(out, np.int64))),
           _wrap_value(jnp.asarray(np.asarray(counts, np.int64))))
    if return_eids:
        res += (_wrap_value(jnp.asarray(np.asarray(out_eids, np.int64))),)
    return res
