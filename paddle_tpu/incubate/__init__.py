"""paddle_tpu.incubate — incubating APIs (parity: python/paddle/incubate).

- ``incubate.nn``: fused transformer layers (Pallas-flash backed)
- ``incubate.optimizer``: LookAhead, ModelAverage
- ``incubate.autotune``: kernel/dataloader autotune config (reference
  python/paddle/incubate/autotune.py — on TPU, XLA autotunes; the knobs are
  recorded and the flash-attention toggle is honored)
- ``incubate.distributed``: MoE re-export (reference
  incubate/distributed/models/moe)
- ``incubate.asp``: n:m structured sparsity (fluid/contrib/sparsity parity)
- graph ops: graph_send_recv / graph_reindex / fused softmax-mask
  (incubate/operators parity; segment_* reductions under XLA)
"""
from __future__ import annotations

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401 — n:m structured sparsity (contrib/sparsity parity)
from .graph_ops import (  # noqa: F401
    graph_reindex,
    graph_send_recv,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)

from ..autograd import functional as autograd  # noqa: F401 — jacobian/hessian (incubate.autograd parity)


class _MoENamespace:
    @property
    def MoELayer(self):
        from ..distributed.moe import MoELayer

        return MoELayer


class _DistributedModels:
    moe = _MoENamespace()


class _Distributed:
    models = _DistributedModels()


distributed = _Distributed()

_autotune_config = {"kernel": {"enable": True}, "dataloader": {"enable": False}, "layout": {"enable": False}}


def autotune_config():
    return dict(_autotune_config)


class autotune:
    """incubate.autotune.set_config parity."""

    @staticmethod
    def set_config(config=None):
        from ..framework.flags import set_flags

        if not config:
            return
        _autotune_config.update(config)
        kern = config.get("kernel", {})
        if "enable" in kern:
            set_flags({"FLAGS_use_flash_attention": bool(kern["enable"])})


class _PrimState:
    """incubate.autograd prim-op switches (reference
    python/paddle/incubate/autograd/primx.py + enable_prim). Under JAX every
    op already lowers to differentiable primitives and composes with
    forward-/reverse-mode (jvp/vjp/jacobian/hessian in
    paddle_tpu.autograd.functional), so the switch records intent only."""

    enabled = False


def enable_prim():
    _PrimState.enabled = True


def disable_prim():
    _PrimState.enabled = False


def prim_enabled():
    return _PrimState.enabled


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode AD (reference incubate/autograd/primapi.py forward_grad):
    jvp of the graph from ``inputs`` to ``outputs``."""
    from ..autograd import jvp as _jvp

    raise NotImplementedError(
        "use paddle_tpu.autograd.jvp(func, xs, v) — forward-mode requires "
        "the function form (JAX traces functions, not taped graphs)")
