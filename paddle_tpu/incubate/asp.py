"""ASP — automatic structured (n:m) sparsity.

Parity: python/paddle/fluid/contrib/sparsity/{asp.py,utils.py} —
``prune_model`` computes n:m fine-grained masks over supported layers'
weights (mask_1d best-magnitude selection), ``decorate`` wraps the
optimizer so masked weights stay zero through updates (the reference
inserts mask-mul ops after each optimizer op; here the mask is re-applied
functionally after ``step()``), ``calculate_density`` / ``check_sparsity``
are the audit helpers.

TPU note: n:m sparsity on TPU is a *model compression* feature (smaller
checkpoints, distillation targets) — there is no sparse-MXU speedup to
claim, so masks apply as dense multiplies XLA folds into adjacent ops.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "decorate", "prune_model", "calculate_density", "check_sparsity",
    "create_mask", "set_excluded_layers", "reset_excluded_layers",
]

_EXCLUDED: Dict[int, set] = {}
_MASKS: Dict[int, np.ndarray] = {}  # id(param) -> mask


def calculate_density(x) -> float:
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / max(x.size, 1)


def create_mask(tensor, func_name="mask_1d", n=2, m=4) -> np.ndarray:
    """Keep the n largest-|x| entries in every group of m along the last
    axis (reference get_mask_1d)."""
    if func_name not in ("mask_1d", "mask_2d_greedy", "mask_2d_best"):
        raise NotImplementedError(func_name)
    t = np.asarray(tensor)
    flat = t.reshape(-1, t.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, [(0, 0), (0, pad)])
    g = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(-np.abs(g), axis=-1)
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, order[..., :n], True, axis=-1)
    mask = mask.reshape(flat.shape)[:, :cols]
    return mask.reshape(t.shape).astype(t.dtype)


def check_sparsity(tensor, n=2, m=4, func_name="mask_1d") -> bool:
    """True iff every m-group along the last axis has <= n nonzeros."""
    t = np.asarray(tensor)
    flat = t.reshape(-1, t.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, [(0, 0), (0, pad)])
    g = flat.reshape(flat.shape[0], -1, m)
    return bool((np.count_nonzero(g, axis=-1) <= n).all())


def set_excluded_layers(main_program=None, param_names=None, model=None):
    names = set(param_names or [])
    _EXCLUDED[id(model)] = names


def reset_excluded_layers(main_program=None, model=None):
    _EXCLUDED.pop(id(model), None)


def _prunable_params(model):
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D

    excluded = _EXCLUDED.get(id(model), set())
    out = []
    for name, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, (Linear, Conv2D)) and layer.weight is not None:
            pname = layer.weight.name or f"{name}.weight"
            if pname not in excluded and name not in excluded:
                out.append(layer.weight)
    return out


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune supported layers (Linear/Conv2D) to n:m sparsity in place;
    record masks so a decorated optimizer keeps them enforced. Returns
    {param_name: mask}."""
    import jax.numpy as jnp

    masks = {}
    for p in _prunable_params(model):
        mask = create_mask(np.asarray(p._value), mask_algo, n, m)
        p._value = p._value * jnp.asarray(mask)
        if with_mask:
            _MASKS[id(p)] = mask
        masks[p.name or str(id(p))] = mask
    return masks


def decorate(optimizer):
    """Wrap ``optimizer.step`` so recorded masks re-apply after every update
    (reference ASPHelper._insert_sparse_mask_ops)."""
    import jax.numpy as jnp

    orig_step = optimizer.step

    def step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for p in optimizer._params:
            mask = _MASKS.get(id(p))
            if mask is not None:
                p._value = p._value * jnp.asarray(mask)
        return out

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer
