"""Graph-learning operators.

Parity: python/paddle/incubate/operators/graph_send_recv.py (+ the
graph_reindex / sample-neighbors family) and the fused softmax-mask ops
(softmax_mask_fuse.py, softmax_mask_fuse_upper_triangle.py).

TPU-first: message passing is gather + segment reduction —
``jax.ops.segment_*`` compiles to one fused scatter per pool type, which IS
the memory-saving fusion the reference's CUDA kernel provides (no
[num_edges, F] intermediate in HBM after XLA fuses the gather into the
scatter). Neighbor sampling is host-side (numpy) by nature — it produces
data-dependent shapes, which belong outside the compiled graph.
"""
from __future__ import annotations

import numpy as np

from ..tensor._helpers import ensure_tensor, op

__all__ = ["graph_send_recv", "graph_reindex", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None, name=None):
    """Gather ``x[src_index]``, reduce into ``dst_index`` slots.

    pool_type: sum | mean | max | min. Output rows with no incoming message
    are 0 (sum/mean, reference semantics) or 0 for max/min (the reference
    fills with 0, not ±inf)."""
    import jax
    import jax.numpy as jnp

    pool = pool_type.lower()
    if pool not in ("sum", "mean", "max", "min"):
        raise ValueError(f"pool_type must be sum/mean/max/min, got {pool_type}")

    x = ensure_tensor(x)
    n_out = int(out_size) if out_size is not None else int(x._value.shape[0])

    def fn(xv, src, dst):
        src = src.astype(jnp.int32)
        dst = dst.astype(jnp.int32)
        msgs = jnp.take(xv, src, axis=0)
        if pool == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        if pool == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
            cnt = jax.ops.segment_sum(jnp.ones_like(dst, xv.dtype), dst, num_segments=n_out)
            return s / jnp.maximum(cnt, 1)[(...,) + (None,) * (xv.ndim - 1)]
        if pool == "max":
            r = jax.ops.segment_max(msgs, dst, num_segments=n_out)
        else:
            r = jax.ops.segment_min(msgs, dst, num_segments=n_out)
        # unreceived slots come back ±inf from segment_max/min; reference
        # leaves them 0
        return jnp.where(jnp.isfinite(r), r, jnp.zeros_like(r))

    return op(fn, x, ensure_tensor(src_index), ensure_tensor(dst_index), _name="graph_send_recv")


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None, flag_buffer_hashtable=False, name=None):
    """Reindex a sampled subgraph to contiguous local ids (reference
    graph_reindex.py). Host-side numpy: the output shapes are data-dependent
    (unique node count), so this runs outside jit by design.

    Returns (reindex_src, reindex_dst, out_nodes)."""
    from ..framework.core import _wrap_value
    import jax.numpy as jnp

    xv = np.asarray(ensure_tensor(x).numpy()).reshape(-1)
    nb = np.asarray(ensure_tensor(neighbors).numpy()).reshape(-1)
    cnt = np.asarray(ensure_tensor(count).numpy()).reshape(-1)

    out_nodes = list(xv)
    seen = {int(v): i for i, v in enumerate(xv)}
    for v in nb:
        v = int(v)
        if v not in seen:
            seen[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.array([seen[int(v)] for v in nb], np.int64)
    dst = np.repeat(np.arange(len(xv), dtype=np.int64), cnt)
    return (_wrap_value(jnp.asarray(reindex_src)),
            _wrap_value(jnp.asarray(dst)),
            _wrap_value(jnp.asarray(np.array(out_nodes, np.int64))))


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) (reference fused_softmax_mask op) — XLA fuses the
    add into the softmax; the op exists for API parity."""
    import jax

    return op(lambda a, m: jax.nn.softmax(a + m, axis=-1),
              ensure_tensor(x), ensure_tensor(mask), _name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the upper triangle masked out (causal mask fused;
    reference softmax_mask_fuse_upper_triangle)."""
    import jax
    import jax.numpy as jnp

    def fn(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(mask, a, -1e9), axis=-1)

    return op(fn, ensure_tensor(x), _name="softmax_mask_fuse_upper_triangle")
