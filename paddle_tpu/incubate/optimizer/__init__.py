"""paddle_tpu.incubate.optimizer — LookAhead, ModelAverage.

Parity: reference python/paddle/incubate/optimizer/{lookahead,modelaverage}.py.
Both wrap an inner optimizer's eager step with slow-weight bookkeeping kept as
jax arrays; they compose with the jit TrainStep by wrapping step() only (the
reference implements them as extra ops appended after the inner update).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k fast steps, then slow weights interpolate: slow += alpha*(fast-slow)
    (reference lookahead.py:30)."""

    def __init__(self, inner_optimizer: Optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha, self.k = float(alpha), int(k)
        self._params = inner_optimizer._params
        self._grad_clip = inner_optimizer._grad_clip
        self._weight_decay = inner_optimizer._weight_decay
        self._lr = inner_optimizer._lr
        self.core = inner_optimizer.core
        self._state = None
        self._step_count = 0
        self._slow = None

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        if self._slow is None:
            self._slow = {id(p): p._value for p in self._params if not p.stop_gradient}
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self._params:
                if p.stop_gradient:
                    continue
                slow = self._slow[id(p)] + self.alpha * (p._value - self._slow[id(p)])
                self._slow[id(p)] = slow
                p._value = slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        out["lookahead_step"] = self._step_count
        if self._slow is not None:
            # slow weights persist like the reference's accumulators; keyed
            # positionally since id() is not stable across processes
            order = [id(p) for p in self._params if not p.stop_gradient]
            out["lookahead_slow"] = [np.asarray(self._slow[i]) for i in order]
        return out

    def set_state_dict(self, state):
        state = dict(state)
        self._step_count = int(state.pop("lookahead_step", self._step_count))
        slow = state.pop("lookahead_slow", None)
        if slow is not None:
            trainable = [p for p in self._params if not p.stop_gradient]
            if len(trainable) != len(slow):
                raise ValueError(
                    f"lookahead_slow has {len(slow)} entries but the optimizer "
                    f"tracks {len(trainable)} trainable params — param list "
                    "changed since the checkpoint was saved")
            for p, v in zip(trainable, slow):
                if tuple(p._value.shape) != tuple(np.shape(v)):
                    raise ValueError(
                        f"lookahead_slow shape {np.shape(v)} does not match "
                        f"param shape {tuple(p._value.shape)}")
            self._slow = {id(p): jnp.asarray(v) for p, v in zip(trainable, slow)}
        self.inner_optimizer.set_state_dict(state)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None


class ModelAverage(Optimizer):
    """Maintain a running average of parameters; apply()/restore() swap it in
    and out (reference modelaverage.py:35, average window semantics
    simplified to a cumulative mean over min_average_window..max)."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._params = list(parameters) if parameters is not None else []
        self._grad_clip = None
        self._weight_decay = None
        self._lr = 0.0
        self._state = None
        self._step_count = 0
        self._sum = {}
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate current params into the average (called after the inner
        optimizer's step)."""
        for p in self._params:
            if p.stop_gradient:
                continue
            self._sum[id(p)] = self._sum.get(id(p), jnp.zeros_like(p._value)) + p._value  # noqa: PTA305 (keyed by param identity — bounded by model size, not request count)
        self._count += 1
        self._step_count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged params in (context-manager style use: with
        ma.apply(): evaluate)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._swap_in()
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def _swap_in(self):
        if self._count == 0:
            return
        self._backup = {}
        for p in self._params:
            if p.stop_gradient or id(p) not in self._sum:
                continue
            self._backup[id(p)] = p._value
            p._value = self._sum[id(p)] / self._count

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = None

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        raise RuntimeError("ModelAverage tracks another optimizer's params; call step() after it")
