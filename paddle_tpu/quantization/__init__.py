"""Int8 quantization: post-training quantization + fake-quant layers.

Parity targets: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py (PTQ: observer passes over a calibration
loader, per-channel ``channel_wise_abs_max`` weights + per-tensor ``abs_max``
activations), imperative/ptq.py (ImperativePTQ), and paddle.nn.quant's fake
quant layers.

TPU-first: quantization is a graph transform, not a kernel swap. A
quantized layer stores int8 weights + f32 scales; at call time the weight
dequantizes (``w_int8 * scale``) into the matmul — XLA folds the dequant
into the convolution/dot epilogue, and the int8 constants are what lands in
the exported StableHLO artifact (verifiable by scanning the serialized
bytes for the i8 weight tensors). Activation scales (collected by forward
hooks during ``quantize()``'s calibration pass) drive optional fake-quant
of inputs — the numerics contract of the reference's QDQ pairs.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, _wrap_value, unwrap
from ..nn.layer.base import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..tensor._helpers import ensure_tensor, op

__all__ = [
    "PostTrainingQuantization", "ImperativePTQ", "QuantizedLinear",
    "QuantizedConv2D", "quant_abs_max", "dequant", "fake_quant",
]


def quant_abs_max(w: np.ndarray, channel_axis: Optional[int] = None):
    """int8 symmetric quantization. Per-channel when ``channel_axis`` given
    (reference channel_wise_abs_max), else per-tensor abs_max.
    Returns (int8 array, f32 scale broadcastable against w)."""
    w = np.asarray(w, np.float32)
    if channel_axis is None:
        scale = np.maximum(np.abs(w).max(), 1e-8) / 127.0
        scale = np.asarray(scale, np.float32)
    else:
        axes = tuple(i for i in range(w.ndim) if i != channel_axis)
        scale = np.maximum(np.abs(w).max(axis=axes, keepdims=True), 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def dequant(q, scale):
    return jnp.asarray(q, jnp.float32) * jnp.asarray(scale)


def fake_quant(x, scale):
    """Simulated activation quantization (QDQ pair, reference
    quantization_pass.py insert_quant_dequant): round(x/s)·s clipped to
    int8 range. Straight-through in backward (it's used at inference)."""
    s = jnp.asarray(scale, jnp.float32)
    return jnp.clip(jnp.round(x / s), -127, 127) * s


class _QuantizedBase(Layer):
    quant_bits = 8

    def _store_weight(self, weight, channel_axis):
        q, scale = quant_abs_max(np.asarray(unwrap(weight)), channel_axis)
        # int8 payload + f32 scale are buffers: they export as constants and
        # round-trip through state_dict
        self.register_buffer("weight_int8", _wrap_value(jnp.asarray(q)))
        self.register_buffer("weight_scale", _wrap_value(jnp.asarray(scale)))

    def _dequant_weight(self, dtype):
        def fn(q, s):
            return (q.astype(jnp.float32) * s).astype(dtype)

        return op(fn, self.weight_int8, self.weight_scale, _name="dequantize_weight")


class QuantizedLinear(_QuantizedBase):
    """Linear with int8 weight [in, out], per-output-channel scales."""

    def __init__(self, src: Linear, act_scale: Optional[float] = None):
        super().__init__()
        self._store_weight(src.weight, channel_axis=1)
        self.bias = src.bias
        self.act_scale = act_scale
        self._dtype = src.weight._value.dtype

    def forward(self, x):
        from ..nn import functional as F

        x = ensure_tensor(x)
        if self.act_scale is not None:
            x = op(lambda v: fake_quant(v, self.act_scale).astype(v.dtype), x, _name="fake_quant")
        return F.linear(x, self._dequant_weight(self._dtype), self.bias)


class QuantizedConv2D(_QuantizedBase):
    """Conv2D with int8 weight [out, in, kh, kw], per-out-channel scales."""

    def __init__(self, src: Conv2D, act_scale: Optional[float] = None):
        super().__init__()
        self._store_weight(src.weight, channel_axis=0)
        self.bias = src.bias
        self.act_scale = act_scale
        self._dtype = src.weight._value.dtype
        self._stride, self._padding = src.stride, src.padding
        self._dilation, self._groups = src.dilation, src.groups
        self._data_format = src.data_format

    def forward(self, x):
        from ..nn import functional as F

        x = ensure_tensor(x)
        if self.act_scale is not None:
            x = op(lambda v: fake_quant(v, self.act_scale).astype(v.dtype), x, _name="fake_quant")
        return F.conv2d(x, self._dequant_weight(self._dtype), self.bias,
                        self._stride, self._padding, self._dilation, self._groups,
                        self._data_format)


_QUANTIZABLE = {Linear: QuantizedLinear, Conv2D: QuantizedConv2D}


class PostTrainingQuantization:
    """Imperative PTQ (reference post_training_quantization.py:117 API shape,
    imperative flow of slim/quantization/imperative/ptq.py).

    1. calibration: run ``batch_nums`` batches from ``data_loader`` through
       the model with observers (forward hooks) recording per-layer
       activation abs_max;
    2. quantize: swap every quantizable sublayer for its int8 twin;
    3. ``save_quantized_model``: export through jit.save so
       ``paddle.inference.create_predictor`` serves the int8 artifact.
    """

    def __init__(self, model: Layer = None, data_loader=None, batch_nums=8,
                 algo="abs_max", weight_quantize_type="channel_wise_abs_max",
                 quantizable_op_type=("conv2d", "linear"), activation_quantize=False,
                 executor=None, **compat_kwargs):
        if model is None:
            raise ValueError("pass the Layer to quantize as model=")
        if algo not in ("abs_max", "avg"):
            raise NotImplementedError(f"activation algo {algo!r}; use 'abs_max' or 'avg'")
        if weight_quantize_type not in ("channel_wise_abs_max", "abs_max"):
            raise NotImplementedError(weight_quantize_type)
        self.model = model
        self.loader = data_loader
        self.batch_nums = batch_nums
        self.algo = algo
        self.weight_quantize_type = weight_quantize_type
        self.op_types = set(quantizable_op_type)
        self.activation_quantize = activation_quantize
        self._act_stats: Dict[int, List[float]] = {}
        self._quantized = None

    # -- calibration -------------------------------------------------------
    def _observe(self):
        handles = []
        targets = self._targets()
        for lid, (name, layer) in targets.items():
            def mk(lid):
                def hook(layer, inputs, output=None):
                    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
                    self._act_stats.setdefault(lid, []).append(
                        float(jnp.abs(unwrap(ensure_tensor(x))).max()))
                return hook

            handles.append(layer.register_forward_pre_hook(mk(lid)))
        return handles

    def _targets(self):
        out = {}
        for name, layer in self.model.named_sublayers():
            if isinstance(layer, Linear) and "linear" in self.op_types:
                out[id(layer)] = (name, layer)
            elif isinstance(layer, Conv2D) and "conv2d" in self.op_types:
                out[id(layer)] = (name, layer)
        return out

    def quantize(self) -> Layer:
        was_training = self.model.training
        self.model.eval()
        if self.loader is not None:
            handles = self._observe()
            for i, batch in enumerate(self.loader):
                if i >= self.batch_nums:
                    break
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                self.model(ensure_tensor(x))
            for h in handles:
                h.remove()
        if was_training:
            self.model.train()

        # swap quantizable sublayers in place on a reference-holding walk
        def swap(parent):
            for cname, child in list(parent._sub_layers.items()):
                if isinstance(child, (Linear, Conv2D)) and id(child) in self._targets():
                    stats = self._act_stats.get(id(child))
                    act_scale = None
                    if self.activation_quantize and stats:
                        amax = (np.mean(stats) if self.algo == "avg" else np.max(stats))
                        act_scale = float(max(amax, 1e-8) / 127.0)
                    qcls = QuantizedLinear if isinstance(child, Linear) else QuantizedConv2D
                    parent._sub_layers[cname] = qcls(child, act_scale)
                else:
                    swap(child)

        swap(self.model)
        self._quantized = self.model
        return self.model

    def save_quantized_model(self, path, input_spec=None, **kwargs):
        from ..jit import save as jit_save

        if self._quantized is None:
            self.quantize()
        return jit_save(self._quantized, path, input_spec=input_spec)


class ImperativePTQ(PostTrainingQuantization):
    """Name parity with slim/quantization/imperative/ptq.py — same flow."""
