"""Int8 quantization: post-training quantization + fake-quant layers.

Parity targets: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py (PTQ: observer passes over a calibration
loader, per-channel ``channel_wise_abs_max`` weights + per-tensor ``abs_max``
activations), imperative/ptq.py (ImperativePTQ), and paddle.nn.quant's fake
quant layers.

TPU-first: quantization is a graph transform, not a kernel swap. A
quantized layer stores int8 weights + f32 scales; at call time the weight
dequantizes (``w_int8 * scale``) into the matmul — XLA folds the dequant
into the convolution/dot epilogue, and the int8 constants are what lands in
the exported StableHLO artifact (verifiable by scanning the serialized
bytes for the i8 weight tensors). Activation scales (collected by forward
hooks during ``quantize()``'s calibration pass) drive optional fake-quant
of inputs — the numerics contract of the reference's QDQ pairs.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, _wrap_value, unwrap
from ..nn.layer.base import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..tensor._helpers import ensure_tensor, op

__all__ = [
    "PostTrainingQuantization", "ImperativePTQ", "QuantizedLinear",
    "QuantizedConv2D", "quant_abs_max", "dequant", "fake_quant",
    "ImperativeQuantAware", "QATQuantizedLinear", "QATQuantizedConv2D",
]


def quant_abs_max(w: np.ndarray, channel_axis=None):
    """int8 symmetric quantization. Per-channel when ``channel_axis`` given
    (reference channel_wise_abs_max), else per-tensor abs_max. A tuple
    ``channel_axis`` keeps a scale per index along EVERY listed axis — the
    form the serving engine uses for [L, in, out]-stacked trunk weights
    (per-layer × per-output-channel scales, axis (0, 2)).
    Returns (int8 array, f32 scale broadcastable against w)."""
    w = np.asarray(w, np.float32)
    if channel_axis is None:
        scale = np.maximum(np.abs(w).max(), 1e-8) / 127.0
        scale = np.asarray(scale, np.float32)
    else:
        keep = (channel_axis,) if isinstance(channel_axis, int) else tuple(channel_axis)
        keep = tuple(a % w.ndim for a in keep)
        axes = tuple(i for i in range(w.ndim) if i not in keep)
        scale = np.maximum(np.abs(w).max(axis=axes, keepdims=True), 1e-8) / 127.0
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return q, scale.astype(np.float32)


def dequant(q, scale):
    return jnp.asarray(q, jnp.float32) * jnp.asarray(scale)


def fake_quant(x, scale):
    """Simulated activation quantization (QDQ pair, reference
    quantization_pass.py insert_quant_dequant): round(x/s)·s clipped to
    int8 range. Straight-through in backward (it's used at inference)."""
    s = jnp.asarray(scale, jnp.float32)
    return jnp.clip(jnp.round(x / s), -127, 127) * s


class _QuantizedBase(Layer):
    quant_bits = 8

    def _store_weight(self, weight, channel_axis):
        q, scale = quant_abs_max(np.asarray(unwrap(weight)), channel_axis)
        # int8 payload + f32 scale are buffers: they export as constants and
        # round-trip through state_dict
        self.register_buffer("weight_int8", _wrap_value(jnp.asarray(q)))
        self.register_buffer("weight_scale", _wrap_value(jnp.asarray(scale)))

    def _dequant_weight(self, dtype):
        def fn(q, s):
            return (q.astype(jnp.float32) * s).astype(dtype)

        return op(fn, self.weight_int8, self.weight_scale, _name="dequantize_weight")


class QuantizedLinear(_QuantizedBase):
    """Linear with int8 weight [in, out], per-output-channel scales."""

    def __init__(self, src: Linear, act_scale: Optional[float] = None):
        super().__init__()
        self._store_weight(src.weight, channel_axis=1)
        self.bias = src.bias
        self.act_scale = act_scale
        self._dtype = src.weight._value.dtype

    def forward(self, x):
        from ..nn import functional as F

        x = ensure_tensor(x)
        if self.act_scale is not None:
            x = op(lambda v: fake_quant(v, self.act_scale).astype(v.dtype), x, _name="fake_quant")
        return F.linear(x, self._dequant_weight(self._dtype), self.bias)


class QuantizedConv2D(_QuantizedBase):
    """Conv2D with int8 weight [out, in, kh, kw], per-out-channel scales."""

    def __init__(self, src: Conv2D, act_scale: Optional[float] = None):
        super().__init__()
        self._store_weight(src.weight, channel_axis=0)
        self.bias = src.bias
        self.act_scale = act_scale
        self._dtype = src.weight._value.dtype
        self._stride, self._padding = src.stride, src.padding
        self._dilation, self._groups = src.dilation, src.groups
        self._data_format = src.data_format

    def forward(self, x):
        from ..nn import functional as F

        x = ensure_tensor(x)
        if self.act_scale is not None:
            x = op(lambda v: fake_quant(v, self.act_scale).astype(v.dtype), x, _name="fake_quant")
        return F.conv2d(x, self._dequant_weight(self._dtype), self.bias,
                        self._stride, self._padding, self._dilation, self._groups,
                        self._data_format)


_QUANTIZABLE = {Linear: QuantizedLinear, Conv2D: QuantizedConv2D}


class PostTrainingQuantization:
    """Imperative PTQ (reference post_training_quantization.py:117 API shape,
    imperative flow of slim/quantization/imperative/ptq.py).

    1. calibration: run ``batch_nums`` batches from ``data_loader`` through
       the model with observers (forward hooks) recording per-layer
       activation abs_max;
    2. quantize: swap every quantizable sublayer for its int8 twin;
    3. ``save_quantized_model``: export through jit.save so
       ``paddle.inference.create_predictor`` serves the int8 artifact.
    """

    def __init__(self, model: Layer = None, data_loader=None, batch_nums=8,
                 algo="abs_max", weight_quantize_type="channel_wise_abs_max",
                 quantizable_op_type=("conv2d", "linear"), activation_quantize=False,
                 executor=None, **compat_kwargs):
        if model is None:
            raise ValueError("pass the Layer to quantize as model=")
        if isinstance(model, (Linear, Conv2D)):
            raise ValueError(
                "PTQ swaps sublayers in place and cannot replace the root "
                "layer; wrap it, e.g. nn.Sequential(layer)")
        if algo not in ("abs_max", "avg"):
            raise NotImplementedError(f"activation algo {algo!r}; use 'abs_max' or 'avg'")
        if weight_quantize_type not in ("channel_wise_abs_max", "abs_max"):
            raise NotImplementedError(weight_quantize_type)
        self.model = model
        self.loader = data_loader
        self.batch_nums = batch_nums
        self.algo = algo
        self.weight_quantize_type = weight_quantize_type
        self.op_types = set(quantizable_op_type)
        self.activation_quantize = activation_quantize
        self._act_stats: Dict[int, List[float]] = {}
        self._quantized = None

    # -- calibration -------------------------------------------------------
    def _observe(self):
        handles = []
        targets = self._targets()
        for lid, (name, layer) in targets.items():
            def mk(lid):
                def hook(layer, inputs, output=None):
                    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
                    self._act_stats.setdefault(lid, []).append(
                        float(jnp.abs(unwrap(ensure_tensor(x))).max()))
                return hook

            handles.append(layer.register_forward_pre_hook(mk(lid)))
        return handles

    def _targets(self):
        out = {}
        for name, layer in self.model.named_sublayers():
            if isinstance(layer, Linear) and "linear" in self.op_types:
                out[id(layer)] = (name, layer)
            elif isinstance(layer, Conv2D) and "conv2d" in self.op_types:
                out[id(layer)] = (name, layer)
        return out

    def quantize(self) -> Layer:
        was_training = self.model.training
        self.model.eval()
        if self.loader is not None:
            handles = self._observe()
            for i, batch in enumerate(self.loader):
                if i >= self.batch_nums:
                    break
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                self.model(ensure_tensor(x))
            for h in handles:
                h.remove()
        if was_training:
            self.model.train()

        # swap quantizable sublayers in place on a reference-holding walk
        def swap(parent):
            for cname, child in list(parent._sub_layers.items()):
                if isinstance(child, (Linear, Conv2D)) and id(child) in self._targets():
                    stats = self._act_stats.get(id(child))
                    act_scale = None
                    if self.activation_quantize and stats:
                        amax = (np.mean(stats) if self.algo == "avg" else np.max(stats))
                        act_scale = float(max(amax, 1e-8) / 127.0)
                    qcls = QuantizedLinear if isinstance(child, Linear) else QuantizedConv2D
                    parent._sub_layers[cname] = qcls(child, act_scale)
                else:
                    swap(child)

        swap(self.model)
        self._quantized = self.model
        return self.model

    def save_quantized_model(self, path, input_spec=None, **kwargs):
        from ..jit import save as jit_save

        if self._quantized is None:
            self.quantize()
        return jit_save(self._quantized, path, input_spec=input_spec)


class ImperativePTQ(PostTrainingQuantization):
    """Name parity with slim/quantization/imperative/ptq.py — same flow."""


# ---------------------------------------------------------------------------
# QAT: quantization-aware training (reference
# slim/quantization/imperative/qat.py ImperativeQuantAware + the fake-quant
# layers of paddle/nn/quant/quant_layers.py)
# ---------------------------------------------------------------------------

import jax  # noqa: E402


@jax.custom_vjp
def _qdq_ste(x, scale):
    """Quantize-dequantize with a straight-through estimator. ``scale`` is
    the int8 step (amax/127), broadcastable against x; scale<=0 means "not
    yet calibrated" and passes through untouched."""
    s = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / s), -127, 127) * s
    return jnp.where(scale > 0, q.astype(x.dtype), x)


def _qdq_fwd(x, scale):
    return _qdq_ste(x, scale), (x, scale)


def _qdq_bwd(res, g):
    x, scale = res
    # clipped STE (reference fake_quantize_dequantize grad): unit gradient
    # inside the representable range, zero outside; scale is non-trainable
    s = jnp.where(scale > 0, scale, jnp.inf)
    mask = (jnp.abs(x) <= 127 * s).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale)


_qdq_ste.defvjp(_qdq_fwd, _qdq_bwd)


class _QATBase(Layer):
    """Fake-quant wrapper holding the ORIGINAL trainable layer: weights are
    quantize-dequantized per forward with fresh per-channel abs_max scales,
    activations with a moving-average abs_max scale buffer (updated in
    training mode through the same buffer side-effect path as BatchNorm
    running stats, so it works inside compiled TrainStep)."""

    def __init__(self, src: Layer, channel_axis: int, moving_rate: float = 0.9):
        super().__init__()
        self.inner = src  # parameters stay trainable and visible
        self._channel_axis = channel_axis
        self._moving_rate = moving_rate
        self.register_buffer("act_scale", _wrap_value(jnp.zeros([], jnp.float32)))

    def _observe_act(self, x):
        from ..nn.functional.norm import _assign_buffer

        amax = op(lambda v: (jnp.abs(v).max() / 127.0).astype(jnp.float32),
                  x.detach(), _name="quant_act_absmax")
        ro = self.act_scale if self.act_scale.stop_gradient else self.act_scale.detach()

        def ema(old, new):
            return jnp.where(old > 0, self._moving_rate * old + (1 - self._moving_rate) * new, new)

        new_scale = op(ema, ro, amax, _name="quant_ema_scale")
        _assign_buffer(self.act_scale, new_scale)
        return new_scale

    def _fq_act(self, x):
        x = ensure_tensor(x)
        scale = self._observe_act(x) if self.training else self.act_scale
        return op(lambda v, s: _qdq_ste(v, s.astype(jnp.float32)).astype(v.dtype),
                  x, scale, _name="fake_quantize_dequantize")

    def _fq_weight(self, w):
        axes = tuple(i for i in range(w.ndim) if i != self._channel_axis % w.ndim)

        def fn(v):
            s = jnp.maximum(jnp.abs(jax.lax.stop_gradient(v)).max(axis=axes, keepdims=True), 1e-8) / 127.0
            return _qdq_ste(v, s)

        return op(fn, w, _name="fake_channel_wise_quantize_dequantize")

    def _final_act_scale(self):
        s = float(np.asarray(unwrap(self.act_scale)))
        return s if s > 0 else None


class QATQuantizedLinear(_QATBase):
    def __init__(self, src: Linear, moving_rate: float = 0.9):
        super().__init__(src, channel_axis=1, moving_rate=moving_rate)

    def forward(self, x):
        from ..nn import functional as F

        return F.linear(self._fq_act(x), self._fq_weight(self.inner.weight), self.inner.bias)

    def _convert(self):
        return QuantizedLinear(self.inner, self._final_act_scale())


class QATQuantizedConv2D(_QATBase):
    def __init__(self, src: Conv2D, moving_rate: float = 0.9):
        super().__init__(src, channel_axis=0, moving_rate=moving_rate)

    def forward(self, x):
        from ..nn import functional as F

        c = self.inner
        return F.conv2d(self._fq_act(x), self._fq_weight(c.weight), c.bias,
                        c.stride, c.padding, c.dilation, c.groups, c.data_format)

    def _convert(self):
        return QuantizedConv2D(self.inner, self._final_act_scale())


class ImperativeQuantAware:
    """Quantization-aware training driver (reference
    slim/quantization/imperative/qat.py:77 ImperativeQuantAware).

    ``quantize(model)`` swaps Linear/Conv2D sublayers in place for fake-quant
    twins (call BEFORE building the optimizer so it owns the live params);
    train as usual — weight scales track the weights, activation scales are
    moving averages; ``save_quantized_model(model, path, input_spec)``
    converts to int8 layers and exports a servable artifact.
    """

    def __init__(self, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_layer_type=("Conv2D", "Linear"), **compat_kwargs):
        if weight_quantize_type not in ("channel_wise_abs_max", "abs_max"):
            raise NotImplementedError(weight_quantize_type)
        if activation_quantize_type != "moving_average_abs_max":
            raise NotImplementedError(activation_quantize_type)
        if (weight_bits, activation_bits) != (8, 8):
            raise NotImplementedError("int8 only")
        self.moving_rate = moving_rate
        self.types = set(quantizable_layer_type)

    def quantize(self, model: Layer) -> Layer:
        if isinstance(model, (Linear, Conv2D)):
            raise ValueError(
                "quantize() swaps sublayers in place and cannot replace the "
                "root layer; wrap it, e.g. nn.Sequential(layer)")
        swapped = 0

        def swap(parent):
            nonlocal swapped
            for cname, child in list(parent._sub_layers.items()):
                if isinstance(child, Linear) and "Linear" in self.types:
                    parent._sub_layers[cname] = QATQuantizedLinear(child, self.moving_rate)
                    swapped += 1
                elif isinstance(child, Conv2D) and "Conv2D" in self.types:
                    parent._sub_layers[cname] = QATQuantizedConv2D(child, self.moving_rate)
                    swapped += 1
                else:
                    swap(child)

        swap(model)
        if swapped == 0:
            raise ValueError(
                f"no quantizable sublayers ({sorted(self.types)}) found in "
                f"{type(model).__name__}; nothing was quantized")
        return model

    def convert(self, model: Layer) -> Layer:
        """Swap fake-quant layers for real int8 layers (in place)."""

        def swap(parent):
            for cname, child in list(parent._sub_layers.items()):
                if isinstance(child, _QATBase):
                    parent._sub_layers[cname] = child._convert()
                else:
                    swap(child)

        swap(model)
        return model

    def save_quantized_model(self, model: Layer, path, input_spec=None, **kwargs):
        from ..jit import save as jit_save

        was_training = model.training
        model.eval()
        self.convert(model)
        out = jit_save(model, path, input_spec=input_spec)
        if was_training:
            model.train()
        return out
