"""Profiler (parity: python/paddle/profiler/profiler.py:271 + C++
platform/profiler).

TPU-first: wraps ``jax.profiler`` — device traces come from XLA/xplane
(the CUPTI analog), host annotations from ``RecordEvent`` →
``jax.profiler.TraceAnnotation`` AND the native host tracer
(csrc/host_tracer.cc ≈ platform/profiler/host_tracer.cc), whose events export
as a chrome trace (chrometracing_logger.cc parity) via ``Profiler.export``.

The dispatch counters that used to live here (PR 3) are now views over the
:mod:`paddle_tpu.observability.metrics` registry — one store for counters,
gauges and histograms; ``counter_inc``/``counters``/``reset_counters`` keep
their exact signatures.
"""
from __future__ import annotations

import contextlib
import time
import warnings
from collections import defaultdict
from enum import Enum
from typing import Optional

import jax


_nlib = None  # cached handle; only Profiler.start pays the one-time build


def _native(build: bool = False):
    """The native tracer lib, or None.

    ``build=False`` (the per-RecordEvent path) never compiles and never takes
    the build lock — it only returns an already-loaded handle, so hot-loop
    annotations cost one cached check when profiling is off.
    """
    global _nlib
    if _nlib is not None or not build:
        return _nlib
    from ..framework import native

    try:
        _nlib = native.load_native()
    except RuntimeError:  # pragma: no cover - g++ is baked into the image
        pass
    return _nlib


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class RecordEvent:
    """Host-side annotation (parity: platform/profiler/event_tracing.h
    RecordEvent) that also shows up in the device trace."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self.begin_ns = None
        self.end_ns = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self.begin_ns = time.perf_counter_ns()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        lib = _native()
        if lib is not None and lib.pt_trace_enabled():
            lib.pt_trace_begin(self.name.encode(), b"host")
            self._native_open = True

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if getattr(self, "_native_open", False):
            _native().pt_trace_end()
            self._native_open = False
        self.end_ns = time.perf_counter_ns()
        # spans belong to a profiling session; without one, the buffer must
        # not grow — long training loops annotate every step and would
        # otherwise leak one tuple per span forever
        if _session_active:
            _HOST_EVENTS[self.name].append((self.begin_ns, self.end_ns))


_HOST_EVENTS = defaultdict(list)
_session_active = False  # set by Profiler.start/stop: gates _HOST_EVENTS

# ---------------------------------------------------------------- counters
# Monotonic dispatch counters (reference: the op/run counts platform/profiler
# keeps per tracer), now backed by the observability metrics registry:
#   executor.runs / executor.cache_hits / executor.cache_misses /
#   executor.compiles / executor.donated_runs — Executor.run bookkeeping
#   train_step.dispatches / train_step.steps — TrainStep __call__/run_steps
# ``run_steps(k)`` adds 1 dispatch and k steps: dispatches-per-step is the
# amortization ratio bench.py reports.


def counter_inc(name: str, n: int = 1) -> None:
    """Bump a named dispatch counter by ``n``."""
    from ..observability import metrics

    metrics.counter_inc(name, n)


def counters(prefix: str = "") -> dict:
    """Snapshot of the counters, optionally filtered by name prefix."""
    from ..observability import metrics

    return metrics.counters(prefix)


def reset_counters(prefix: str = "") -> None:
    """Zero the counters (those matching ``prefix`` when given)."""
    from ..observability import metrics

    metrics.reset_counters(prefix)


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, record_shapes=False, profile_memory=False, with_flops=False):
        self.timer_only = timer_only
        self.log_dir = None
        self._running = False
        self._t0 = None
        self._t1 = None
        self._step_marks = []  # perf_counter_ns at each step() boundary

    def start(self):
        import tempfile

        global _session_active
        _HOST_EVENTS.clear()  # spans belong to one profiling session
        lib = _native(build=True)
        if lib is not None:
            lib.pt_trace_clear()
            lib.pt_trace_enable(1)
        if not self.timer_only:
            self.log_dir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            jax.profiler.start_trace(self.log_dir)
        self._running = True
        _session_active = True
        self._t0 = time.perf_counter()
        self._t1 = None
        self._step_marks = [time.perf_counter_ns()]

    def stop(self):
        global _session_active
        if self._t0 is None:
            warnings.warn("Profiler.stop() called but start() never ran; "
                          "no profiling session to stop (no-op)", stacklevel=2)
            return
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
        lib = _native()
        if lib is not None:
            lib.pt_trace_enable(0)
        self._running = False
        _session_active = False
        self._t1 = time.perf_counter()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def step(self, num_samples=None):
        """Mark a training-step boundary (reference Profiler.step drives the
        scheduler state machine; here it records the boundary so summaries
        report per-step timings). Bumps the ``profiler.steps`` counter and,
        during a session, appends the elapsed step span to the host trace
        (exported as a ``profiler.step`` span in the chrome trace)."""
        counter_inc("profiler.steps")
        if not self._running:
            return
        now = time.perf_counter_ns()
        prev = self._step_marks[-1] if self._step_marks else now
        self._step_marks.append(now)
        _HOST_EVENTS["profiler.step"].append((prev, now))
        lib = _native()
        if lib is not None and lib.pt_trace_enabled():
            lib.pt_trace_instant(b"profiler.step", b"host")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        if self._t0 is None:
            out = "no profiling session (start() never ran)"
            print(out)
            return out
        end = self._t1 if self._t1 is not None else time.perf_counter()
        lines = [f"wall time: {(end - self._t0) * 1000:.2f} ms"]
        if self.log_dir:
            lines.append(f"device trace: {self.log_dir} (open with TensorBoard/perfetto)")
        if len(self._step_marks) > 1:
            spans = [(e - b) / 1e6 for b, e in zip(self._step_marks, self._step_marks[1:])]
            lines.append(f"steps: {len(spans)} mean={sum(spans) / len(spans):.3f} ms "
                         f"min={min(spans):.3f} ms max={max(spans):.3f} ms")
        for name, spans in _HOST_EVENTS.items():
            total_ms = sum(e - b for b, e in spans) / 1e6
            lines.append(f"{name}: calls={len(spans)} total={total_ms:.3f} ms")
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path, format="json"):
        """Write the host-event chrome trace to ``path`` (device trace stays
        in ``self.log_dir`` as an xplane for TensorBoard/perfetto)."""
        if self._t0 is None:
            warnings.warn("Profiler.export() called but start() never ran; "
                          "nothing to export (no-op)", stacklevel=2)
            return None
        lib = _native(build=True)
        if lib is not None:
            if lib.pt_trace_export(str(path).encode(), b"paddle_tpu") != 0:
                raise OSError(f"failed to export trace to {path}")
            return path
        # no native toolchain: still honor the contract from python-side spans
        import json

        events = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                   "args": {"name": "paddle_tpu"}}]
        for name, spans in _HOST_EVENTS.items():
            for b, e in spans:
                events.append({"name": name, "cat": "host", "ph": "X", "pid": 0,
                               "tid": 0, "ts": b / 1000, "dur": (e - b) / 1000})
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        return path


@contextlib.contextmanager
def profile(log_dir: Optional[str] = None):
    """Simple context: jax.profiler.trace wrapper."""
    import tempfile

    d = log_dir or tempfile.mkdtemp(prefix="paddle_tpu_prof_")
    with jax.profiler.trace(d):
        yield d


def export_chrome_tracing(dir_name: str, worker_name=None):
    def handler(prof):
        return dir_name

    return handler


def start_profiler(state="All", tracer_option="Default"):
    jax.profiler.start_trace("/tmp/paddle_tpu_profile")


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()
