"""Profiler (parity: python/paddle/profiler/profiler.py:271 + C++
platform/profiler).

TPU-first: wraps ``jax.profiler`` — device traces come from XLA/xplane
(the CUPTI analog), host annotations from ``RecordEvent`` →
``jax.profiler.TraceAnnotation``. Output is a TensorBoard/perfetto trace dir
(chrome-trace parity: chrometracing_logger.cc).
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from enum import Enum
from typing import Optional

import jax


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class RecordEvent:
    """Host-side annotation (parity: platform/profiler/event_tracing.h
    RecordEvent) that also shows up in the device trace."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self.begin_ns = None
        self.end_ns = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self.begin_ns = time.perf_counter_ns()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        self.end_ns = time.perf_counter_ns()
        _HOST_EVENTS[self.name].append((self.begin_ns, self.end_ns))


_HOST_EVENTS = defaultdict(list)


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, record_shapes=False, profile_memory=False, with_flops=False):
        self.timer_only = timer_only
        self.log_dir = None
        self._running = False

    def start(self):
        import tempfile

        _HOST_EVENTS.clear()  # spans belong to one profiling session
        if not self.timer_only:
            self.log_dir = tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            jax.profiler.start_trace(self.log_dir)
        self._running = True
        self._t0 = time.perf_counter()
        self._t1 = None

    def stop(self):
        if self._running and not self.timer_only:
            jax.profiler.stop_trace()
        self._running = False
        self._t1 = time.perf_counter()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def step(self, num_samples=None):
        pass

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        end = self._t1 if self._t1 is not None else time.perf_counter()
        lines = [f"wall time: {(end - self._t0) * 1000:.2f} ms"]
        if self.log_dir:
            lines.append(f"device trace: {self.log_dir} (open with TensorBoard/perfetto)")
        for name, spans in _HOST_EVENTS.items():
            total_ms = sum(e - b for b, e in spans) / 1e6
            lines.append(f"{name}: calls={len(spans)} total={total_ms:.3f} ms")
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path, format="json"):
        return self.log_dir


@contextlib.contextmanager
def profile(log_dir: Optional[str] = None):
    """Simple context: jax.profiler.trace wrapper."""
    import tempfile

    d = log_dir or tempfile.mkdtemp(prefix="paddle_tpu_prof_")
    with jax.profiler.trace(d):
        yield d


def export_chrome_tracing(dir_name: str, worker_name=None):
    def handler(prof):
        return dir_name

    return handler


def start_profiler(state="All", tracer_option="Default"):
    jax.profiler.start_trace("/tmp/paddle_tpu_profile")


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()
