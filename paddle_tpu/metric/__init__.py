"""Metrics (parity: python/paddle/metric/metrics.py — Metric, Accuracy,
Precision, Recall, Auc; + operators/metrics/ accuracy/auc ops)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor, unwrap


def _np(x):
    return np.asarray(unwrap(x)) if not isinstance(x, np.ndarray) else x


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()

    def compute(self, pred, label):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        maxk = max(self.topk)
        topk_idx = np.argsort(-p, axis=-1)[..., :maxk]
        correct = topk_idx == l[..., None]
        return correct, None

    def update(self, correct, _=None):
        c = _np(correct)
        for i, k in enumerate(self.topk):
            acc_k = c[..., :k].any(axis=-1)
            self.total[i] += acc_k.sum()
            self.count[i] += acc_k.size
        return self.accumulate()

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    """Trapezoid AUC over thresholded bins (parity: operators/metrics/auc_op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # accumulate from highest threshold down
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (parity: python/paddle/metric/metrics.py:789)."""
    from ..framework.core import _wrap_value
    import jax.numpy as jnp

    p = _np(input)
    l = _np(label)
    if l.ndim == p.ndim and l.shape[-1] == 1:
        l = l.squeeze(-1)
    topk_idx = np.argsort(-p, axis=-1)[..., :k]
    acc = (topk_idx == l[..., None]).any(axis=-1).mean()
    return _wrap_value(jnp.asarray(acc, jnp.float32))
