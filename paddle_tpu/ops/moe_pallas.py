"""Fused sort-based MoE dispatch/combine — the Pallas kernel tier's MoE op.

Parity target: the reference's MoE hot path (incubate/distributed/models/moe
``global_scatter``/``global_gather`` collectives + per-expert FFNs,
operators/collective/global_scatter_op.cu.cc). The dense GShard composite in
:mod:`paddle_tpu.distributed.moe` routes with a ``[T·K, E]`` one-hot +
cumsum (O(T·K·E) work) and pushes a padded ``[E, capacity, D]`` dispatch
buffer plus its ``[E, capacity, H]`` hidden activations through HBM on every
step. This module replaces that with:

1. **dispatch**: a stable argsort of the T·K (token, expert) pairs by
   expert id — O(TK·log TK) — yielding contiguous per-expert token runs;
   each pair's queue position is its offset from the run start (a
   length-E cumsum), so capacity dropping keeps the dense path's exact
   arrival-order semantics without the [T·K, E] cumsum.
2. **expert FFN**: ONE fused Pallas grouped-matmul kernel over the sorted
   runs — both projections and the activation per row block, streamed over
   H tiles, hidden activations living only in VMEM. The expert weights for
   a block are chosen by static grid arithmetic (each expert's run is
   padded to a whole number of row blocks), so there is no gather inside
   the kernel and no [rows, H] hidden buffer in HBM.
3. **combine**: a weighted scatter-add back to token order.

A ``custom_vjp`` makes it train: the backward is a Pallas kernel pair (a
dx/db2 kernel and a dw1/db1/dw2 kernel, mirroring the flash-attention
dq / dk-dv split so every output block is revisited only on consecutive
grid steps) that recomputes the hidden activations in VMEM instead of
saving them. Everything runs under the Pallas interpreter via
:func:`set_interpret` so CPU tier-1 pins fwd+grad parity against the dense
composite without a TPU.

Registered as implementation ``pallas_sorted`` of the ``moe`` kernel; the
dense composite registers itself as the ``dense`` fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import registry

_BLOCK_ROWS = 128  # row-block (tokens) per grid step; experts pad to a multiple
_BLOCK_H = 512     # hidden tile streamed through VMEM
_INTERPRET = False

__all__ = ["moe_dispatch_combine", "moe_available", "set_interpret"]


def set_interpret(on: bool) -> bool:
    """Route the MoE ``pl.pallas_call``s through the Pallas interpreter —
    the CPU path tier-1 uses to pin kernel math against the dense
    composite without a TPU. Returns the prior setting."""
    global _INTERPRET
    prior = _INTERPRET
    _INTERPRET = bool(on)
    return prior


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _block_sizes(capacity: int, hidden: int):
    """(row block, padded per-expert capacity, hidden tile). The row block
    adapts down for tiny capacities (tests) and the hidden tile to the
    largest 128-multiple divisor so big-H weights stream instead of
    needing a whole [D, H] residency in VMEM. Interpreter mode (no VMEM)
    takes whole-expert row blocks — fewer, larger grid steps."""
    if _INTERPRET:
        # whole-expert row blocks + untiled hidden: no VMEM bound off-TPU,
        # and the whole-problem shape routes _grouped_ffn through the
        # identical-math XLA reference lowering (the interpreter's
        # per-call ref-emulation tax would otherwise dominate)
        bm = _round_up(capacity, 8)
        return bm, bm, hidden
    bm = min(_BLOCK_ROWS, _round_up(capacity, 8))
    cap = _round_up(capacity, bm)
    if hidden <= _BLOCK_H:
        bh = hidden
    else:
        bh = max(b for b in (512, 256, 128) if hidden % b == 0)
    return bm, cap, bh


def moe_available(tokens, gate_vals, gate_idx, drop_mask, w1, b1, w2, b2, *,
                  capacity, activation) -> bool:
    """Availability predicate for the registry: interpret mode accepts any
    shape (the interpreter has no tiling constraints); on a TPU backend the
    model dims must be lane-aligned and the capacity big enough that row
    blocks are MXU-shaped."""
    E, D, H = (int(s) for s in w1.shape)
    if jnp.dtype(tokens.dtype) not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return False
    if H > _BLOCK_H and all(H % b for b in (512, 256, 128)):
        return False
    if _INTERPRET:
        return True
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return D % 128 == 0 and H % 128 == 0 and capacity >= 8


# -- fused grouped-FFN kernels ----------------------------------------------


def _dot32(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())), preferred_element_type=jnp.float32)


_NT = ((1,), (1,))  # a @ b.T
_NN = ((1,), (0,))  # a @ b
_TN = ((0,), (0,))  # a.T @ b


def _ffn_fwd_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, *, act, nh):
    """One (row block, hidden tile) cell: y += act(x @ w1_t + b1_t) @ w2_t,
    accumulated in the f32 output across the (inner) hidden-tile axis. The
    hidden activations never leave VMEM; the backward recomputes them
    (flash-style — HBM traffic, not flops, bounds the TPU hot path)."""
    from jax.experimental import pallas as pl

    hb = pl.program_id(1)
    x = x_ref[...]  # [bm, D]
    s = _dot32(x, w1_ref[...], _NN) + b1_ref[...]  # [bm, bh] f32
    h = act(s)
    part = _dot32(h.astype(x.dtype), w2_ref[...], _NN)  # [bm, D] f32

    @pl.when(hb == 0)
    def _init():
        o_ref[...] = part + b2_ref[...]

    @pl.when(hb > 0)
    def _acc():
        o_ref[...] += part


def _ffn_fwd_small_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, s_ref, *, act):
    """Single-hidden-tile forward (nh == 1): the pre-activation fits one
    block, so it is written out as the vjp residual — the backward then
    recomputes only the elementwise activation, matching the autodiffed
    composite's matmul count (the right trade on CPU interpret and small
    H, where flops beat HBM traffic as the bound)."""
    x = x_ref[...]
    s = _dot32(x, w1_ref[...], _NN) + b1_ref[...]
    s_ref[...] = s
    h = act(s)
    o_ref[...] = (_dot32(h.astype(x.dtype), w2_ref[...], _NN)
                  + b2_ref[...]).astype(o_ref.dtype)


def _reference_ffn_fwd(xg, w1, b1, w2, b2, act, E, cap):
    """Off-TPU lowering of the grouped FFN: the SAME math as the kernels
    (per-expert x@w1+b1 → act → @w2+b2 over the sorted/padded layout, f32
    accumulation, s saved as the vjp residual) as plain batched einsums.
    The Pallas interpreter pays a fixed ref-emulation/copy tax per call
    that swamps problems this small, so the interpret-mode registry path
    runs this lowering; the interpreted kernels themselves are pinned
    against it (and against the dense composite) by the tier-1 tests."""
    R, D = xg.shape
    xs = xg.reshape(E, cap, D)
    s = jnp.einsum("ecd,edh->ech", xs, w1, preferred_element_type=jnp.float32) + b1
    h = act(s)
    y = jnp.einsum("ech,ehd->ecd", h.astype(xg.dtype), w2,
                   preferred_element_type=jnp.float32) + b2
    return y.reshape(R, D).astype(xg.dtype), s


def _reference_ffn_bwd(xg, w1, b1, w2, b2, s, dy, act, E, cap):
    R, D = xg.shape
    xs = xg.reshape(E, cap, D)
    dys = dy.reshape(E, cap, D).astype(xg.dtype)
    h, act_vjp = jax.vjp(act, s)
    dp = jnp.einsum("ecd,ehd->ech", dys, w2, preferred_element_type=jnp.float32)
    dh = act_vjp(dp)[0]
    dx = jnp.einsum("ech,edh->ecd", dh.astype(xg.dtype), w1,
                    preferred_element_type=jnp.float32)
    dw1 = jnp.einsum("ecd,ech->edh", xs, dh.astype(xg.dtype),
                     preferred_element_type=jnp.float32)
    db1 = jnp.sum(dh, axis=1, keepdims=True)
    dw2 = jnp.einsum("ech,ecd->ehd", h.astype(xg.dtype), dys,
                     preferred_element_type=jnp.float32)
    db2 = jnp.sum(dys.astype(jnp.float32), axis=1, keepdims=True)
    return (dx.reshape(R, D).astype(xg.dtype), dw1.astype(w1.dtype),
            db1.astype(b1.dtype), dw2.astype(w2.dtype), db2.astype(b2.dtype))


def _ffn_bwd_fused_kernel(x_ref, dy_ref, s_ref, w1_ref, w2_ref,
                          dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, *, act, bpe):
    """Single-hidden-tile backward (nh == 1): with no hidden-tile axis in
    the grid, dx (per row block) and the weight grads (per expert,
    consecutive row blocks) coexist in ONE kernel, fed by the saved
    pre-activation — only the elementwise activation is recomputed. The
    tiled two-kernel pair below handles nh > 1 with full recompute."""
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    x = x_ref[...]
    dy = dy_ref[...]
    h, act_vjp = jax.vjp(act, s_ref[...])
    dp = _dot32(dy, w2_ref[...], _NT)
    dh = act_vjp(dp)[0]
    dx_ref[...] = _dot32(dh.astype(x.dtype), w1_ref[...], _NT)
    dw1_p = _dot32(x, dh.astype(x.dtype), _TN)
    db1_p = jnp.sum(dh, axis=0, keepdims=True)
    dw2_p = _dot32(h.astype(x.dtype), dy, _TN)
    db2_p = jnp.sum(dy.astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(g % bpe == 0)
    def _init():
        dw1_ref[...] = dw1_p
        db1_ref[...] = db1_p
        dw2_ref[...] = dw2_p
        db2_ref[...] = db2_p

    @pl.when(g % bpe > 0)
    def _acc():
        dw1_ref[...] += dw1_p
        db1_ref[...] += db1_p
        dw2_ref[...] += dw2_p
        db2_ref[...] += db2_p


def _ffn_bwd_dx_kernel(x_ref, dy_ref, w1_ref, b1_ref, w2_ref, dx_ref, db2_ref, *, act, bpe):
    """dx = (act'(s) ∘ (dy @ w2ᵀ)) @ w1ᵀ accumulated over hidden tiles
    (inner axis); db2 = Σ_rows dy accumulated over the expert's row blocks
    (outer axis) — both outputs only ever revisited on consecutive steps."""
    from jax.experimental import pallas as pl

    g, hb = pl.program_id(0), pl.program_id(1)
    x = x_ref[...]
    dy = dy_ref[...]
    s = _dot32(x, w1_ref[...], _NN) + b1_ref[...]
    _, act_vjp = jax.vjp(act, s)
    dp = _dot32(dy, w2_ref[...], _NT)  # [bm, bh]
    dh = act_vjp(dp)[0]
    part = _dot32(dh.astype(x.dtype), w1_ref[...], _NT)  # [bm, D]

    @pl.when(hb == 0)
    def _init_dx():
        dx_ref[...] = part

    @pl.when(hb > 0)
    def _acc_dx():
        dx_ref[...] += part

    dy_sum = jnp.sum(dy.astype(jnp.float32), axis=0, keepdims=True)

    @pl.when((g % bpe == 0) & (hb == 0))
    def _init_db2():
        db2_ref[...] = dy_sum

    @pl.when((g % bpe > 0) & (hb == 0))
    def _acc_db2():
        db2_ref[...] += dy_sum


def _ffn_bwd_dw_kernel(x_ref, dy_ref, w1_ref, b1_ref, w2_ref,
                       dw1_ref, db1_ref, dw2_ref, *, act, bpe):
    """Weight grads per (hidden tile, expert) block, accumulated over the
    expert's row blocks — the grid runs hidden tiles OUTER / row blocks
    INNER so each dw block's revisits are consecutive."""
    from jax.experimental import pallas as pl

    g = pl.program_id(1)
    x = x_ref[...]
    dy = dy_ref[...]
    s = _dot32(x, w1_ref[...], _NN) + b1_ref[...]
    h, act_vjp = jax.vjp(act, s)
    dp = _dot32(dy, w2_ref[...], _NT)
    dh = act_vjp(dp)[0]
    dw1_p = _dot32(x, dh.astype(x.dtype), _TN)          # [D, bh]
    db1_p = jnp.sum(dh, axis=0, keepdims=True)          # [1, bh]
    dw2_p = _dot32(h.astype(x.dtype), dy, _TN)          # [bh, D]

    @pl.when(g % bpe == 0)
    def _init():
        dw1_ref[...] = dw1_p
        db1_ref[...] = db1_p
        dw2_ref[...] = dw2_p

    @pl.when(g % bpe > 0)
    def _acc():
        dw1_ref[...] += dw1_p
        db1_ref[...] += db1_p
        dw2_ref[...] += dw2_p


def _row_specs(bm, D, order):
    """BlockSpecs for the [rows, D] operands; ``order`` maps grid ids to
    (row block, hidden tile) — (g, hb) for the fwd/dx grids, (hb, g) for
    the dw grid."""
    from jax.experimental import pallas as pl

    g_of = (lambda a, b: a) if order == "g_outer" else (lambda a, b: b)
    return pl.BlockSpec((bm, D), lambda a, b, _g=g_of: (_g(a, b), 0))


def _expert_specs(D, bh, bpe, order):
    """BlockSpecs for the per-expert weight operands (w1/b1/w2): expert =
    row block // blocks-per-expert — static grid arithmetic, no gather."""
    from jax.experimental import pallas as pl

    if order == "g_outer":
        e_of, h_of = (lambda a, b: a // bpe), (lambda a, b: b)
    else:
        e_of, h_of = (lambda a, b: b // bpe), (lambda a, b: a)
    return [
        pl.BlockSpec((None, D, bh), lambda a, b: (e_of(a, b), 0, h_of(a, b))),
        pl.BlockSpec((None, 1, bh), lambda a, b: (e_of(a, b), 0, h_of(a, b))),
        pl.BlockSpec((None, bh, D), lambda a, b: (e_of(a, b), h_of(a, b), 0)),
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _grouped_ffn(xg, w1, b1, w2, b2, act, bm, bh):
    """act(xg @ w1[e] + b1[e]) @ w2[e] + b2[e] where e = row // (rows per
    expert); xg is the sorted/padded [E*cap, D] dispatch layout."""
    y, _ = _grouped_ffn_fwd(xg, w1, b1, w2, b2, act, bm, bh)
    return y


def _grouped_ffn_fwd(xg, w1, b1, w2, b2, act, bm, bh):
    from jax.experimental import pallas as pl

    R, D = xg.shape
    E, _, H = w1.shape
    bpe = (R // E) // bm
    nh = H // bh
    b2f = b2.astype(jnp.float32)
    if _INTERPRET and bpe == 1 and nh == 1:
        y, s = _reference_ffn_fwd(xg, w1, b1, w2, b2, act, E, R // E)
        return y, (xg, w1, b1, w2, b2, s)
    if nh == 1:
        y, s = pl.pallas_call(
            functools.partial(_ffn_fwd_small_kernel, act=act),
            grid=(R // bm,),
            in_specs=[
                pl.BlockSpec((bm, D), lambda g: (g, 0)),
                pl.BlockSpec((None, D, H), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, 1, H), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, H, D), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, 1, D), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((bm, D), lambda g: (g, 0)),
                pl.BlockSpec((bm, H), lambda g: (g, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((R, D), xg.dtype),
                jax.ShapeDtypeStruct((R, H), jnp.float32),
            ],
            interpret=_INTERPRET,
        )(xg, w1, b1, w2, b2f)
        return y, (xg, w1, b1, w2, b2, s)
    y = pl.pallas_call(
        functools.partial(_ffn_fwd_kernel, act=act, nh=nh),
        grid=(R // bm, nh),
        in_specs=[_row_specs(bm, D, "g_outer")] + _expert_specs(D, bh, bpe, "g_outer") + [
            pl.BlockSpec((None, 1, D), lambda g, hb, _bpe=bpe: (g // _bpe, 0, 0)),
        ],
        out_specs=_row_specs(bm, D, "g_outer"),
        out_shape=jax.ShapeDtypeStruct((R, D), jnp.float32),
        interpret=_INTERPRET,
    )(xg, w1, b1, w2, b2f)
    return y.astype(xg.dtype), (xg, w1, b1, w2, b2, None)


def _grouped_ffn_bwd(act, bm, bh, res, dy):
    from jax.experimental import pallas as pl

    xg, w1, b1, w2, b2, s_res = res
    R, D = xg.shape
    E, _, H = w1.shape
    bpe = (R // E) // bm
    nh = H // bh
    dyc = dy.astype(xg.dtype)

    if _INTERPRET and bpe == 1 and nh == 1:
        return _reference_ffn_bwd(xg, w1, b1, w2, b2, s_res, dy, act, E, R // E)

    if nh == 1:
        dx, dw1, db1, dw2, db2 = pl.pallas_call(
            functools.partial(_ffn_bwd_fused_kernel, act=act, bpe=bpe),
            grid=(R // bm,),
            in_specs=[
                pl.BlockSpec((bm, D), lambda g: (g, 0)),
                pl.BlockSpec((bm, D), lambda g: (g, 0)),
                pl.BlockSpec((bm, H), lambda g: (g, 0)),
                pl.BlockSpec((None, D, H), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, H, D), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((bm, D), lambda g: (g, 0)),
                pl.BlockSpec((None, D, H), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, 1, H), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, H, D), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
                pl.BlockSpec((None, 1, D), lambda g, _bpe=bpe: (g // _bpe, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((R, D), jnp.float32),
                jax.ShapeDtypeStruct((E, D, H), jnp.float32),
                jax.ShapeDtypeStruct((E, 1, H), jnp.float32),
                jax.ShapeDtypeStruct((E, H, D), jnp.float32),
                jax.ShapeDtypeStruct((E, 1, D), jnp.float32),
            ],
            interpret=_INTERPRET,
        )(xg, dyc, s_res, w1, w2)
        return (dx.astype(xg.dtype), dw1.astype(w1.dtype), db1.astype(b1.dtype),
                dw2.astype(w2.dtype), db2.astype(b2.dtype))

    dx, db2 = pl.pallas_call(
        functools.partial(_ffn_bwd_dx_kernel, act=act, bpe=bpe),
        grid=(R // bm, nh),
        in_specs=[_row_specs(bm, D, "g_outer"), _row_specs(bm, D, "g_outer")]
        + _expert_specs(D, bh, bpe, "g_outer"),
        out_specs=[
            _row_specs(bm, D, "g_outer"),
            pl.BlockSpec((None, 1, D), lambda g, hb, _bpe=bpe: (g // _bpe, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, D), jnp.float32),
            jax.ShapeDtypeStruct((E, 1, D), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(xg, dyc, w1, b1, w2)

    dw1, db1, dw2 = pl.pallas_call(
        functools.partial(_ffn_bwd_dw_kernel, act=act, bpe=bpe),
        grid=(nh, R // bm),
        in_specs=[_row_specs(bm, D, "hb_outer"), _row_specs(bm, D, "hb_outer")]
        + _expert_specs(D, bh, bpe, "hb_outer"),
        out_specs=[
            pl.BlockSpec((None, D, bh), lambda hb, g, _bpe=bpe: (g // _bpe, 0, hb)),
            pl.BlockSpec((None, 1, bh), lambda hb, g, _bpe=bpe: (g // _bpe, 0, hb)),
            pl.BlockSpec((None, bh, D), lambda hb, g, _bpe=bpe: (g // _bpe, hb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((E, D, H), jnp.float32),
            jax.ShapeDtypeStruct((E, 1, H), jnp.float32),
            jax.ShapeDtypeStruct((E, H, D), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(xg, dyc, w1, b1, w2)

    return (dx.astype(xg.dtype), dw1.astype(w1.dtype), db1.astype(b1.dtype),
            dw2.astype(w2.dtype), db2.astype(b2.dtype))


_grouped_ffn.defvjp(_grouped_ffn_fwd, _grouped_ffn_bwd)


# -- public op ---------------------------------------------------------------


def moe_dispatch_combine(tokens, gate_vals, gate_idx, drop_mask, w1, b1, w2, b2, *,
                         capacity, activation):
    """Sort-based dispatch → fused grouped FFN → weighted combine.

    tokens [T, D]; gate_vals/gate_idx [T, K] (top-k routing, k-major per
    token); drop_mask [T, K] bool or None (True = pair not dispatched, e.g.
    GShard random routing — it consumes no capacity); w1 [E, D, H], b1
    [E, 1, H], w2 [E, H, D], b2 [E, 1, D]. ``capacity`` is the per-expert
    token budget; overflow drops in arrival order, exactly matching the
    dense composite. Returns [T, D].
    """
    T, D = tokens.shape
    E, _, H = (int(s) for s in w1.shape)
    K = gate_idx.shape[1]
    N = T * K
    bm, cap, bh = _block_sizes(int(capacity), H)

    flat_e = gate_idx.reshape(-1).astype(jnp.int32)
    if drop_mask is not None:
        # dropped pairs sort past every real expert and never claim a slot
        flat_e = jnp.where(drop_mask.reshape(-1), E, flat_e)
    order = jnp.argsort(flat_e, stable=True).astype(jnp.int32)
    e_sorted = flat_e[order]
    tok_sorted = (order // K).astype(jnp.int32)
    gv_sorted = gate_vals.reshape(-1)[order]

    counts = jnp.bincount(flat_e, length=E + 1)
    starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)  # [E+1] run starts
    pos = jnp.arange(N, dtype=jnp.int32) - starts[e_sorted]
    keep = (e_sorted < E) & (pos < capacity)
    slot = e_sorted * cap + pos

    # dispatch: one scatter of token ids + one gather of token rows
    # (row E*cap and token row T are the write-off lanes for dropped pairs)
    row_ids = jnp.full((E * cap,), T, jnp.int32)
    row_ids = row_ids.at[jnp.where(keep, slot, E * cap)].set(tok_sorted, mode="drop")
    xg = jnp.concatenate([tokens, jnp.zeros((1, D), tokens.dtype)])[row_ids]

    yg = _grouped_ffn(xg, w1, b1, w2, b2, activation, bm, bh)

    # combine: weighted scatter-add back to token order
    weights = jnp.where(keep, gv_sorted, jnp.zeros_like(gv_sorted))
    gathered = yg[jnp.where(keep, slot, 0)] * weights[:, None].astype(yg.dtype)
    return jnp.zeros((T, D), yg.dtype).at[tok_sorted].add(gathered)


registry.define_kernel("moe", cache_key=lambda: ("interpret", _INTERPRET))
registry.register(
    "moe", "pallas_sorted", moe_dispatch_combine, available=moe_available,
    doc="sort-based dispatch + fused Pallas grouped-FFN (TPU, or interpret mode)")
