"""paddle_tpu.ops — the Pallas kernel tier and its registry.

Public surface: the flash-attention kernel families (classic pair +
flat-lane/packed), the fused layer norm, the fused sort-based MoE
dispatch/combine, and the kernel registry every ``nn`` layer dispatches
through (``registry.dispatch(<kernel>, ...)`` with per-signature selection
caching and an XLA-composite fallback).

Note: the ``flash_attention`` *function* is reached as
``ops.flash_attention.flash_attention`` — rebinding it here would shadow
the submodule name existing imports rely on.
"""
from . import flash_attention, flash_attention_flat, layer_norm, moe_pallas, registry  # noqa: F401
from .flash_attention import flash_attention_available, flash_attention_qkv  # noqa: F401
from .flash_attention_flat import flash_flat, flash_flat_gqa, flash_packed  # noqa: F401
from .layer_norm import layer_norm_fused  # noqa: F401
from .moe_pallas import moe_available, moe_dispatch_combine  # noqa: F401
from .registry import (  # noqa: F401
    define_kernel,
    dispatch,
    implementations,
    kernel_table,
    kernels,
    register,
)

__all__ = [
    "flash_attention", "flash_attention_flat", "layer_norm", "moe_pallas",
    "registry",
    "flash_attention_available", "flash_attention_qkv",
    "flash_flat", "flash_flat_gqa", "flash_packed",
    "layer_norm_fused",
    "moe_available", "moe_dispatch_combine",
    "define_kernel", "register", "dispatch", "implementations",
    "kernels", "kernel_table",
]
