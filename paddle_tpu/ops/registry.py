"""Kernel registry: transparent kernel selection with an XLA fallback.

ROADMAP item (Pallas kernel tier): "a registration mechanism so ``nn``
layers transparently pick the kernel when available and fall back to the
XLA composite otherwise". Before this module every call site hand-rolled
its own ``flag(...) and available(...)`` dance; now a kernel name maps to
an ORDERED list of implementations, each with an availability predicate
over the actual call (shapes, dtypes, platform, flags), and the first
accepting implementation wins. The registered fallback — the plain XLA
composite — accepts unconditionally, so dispatch can never fail.

Selection is cached per call signature: array arguments are abstracted to
``(shape, dtype)``, static arguments ride along verbatim, and the cache
key also folds in the backend, the kernel's watched flag values, and
``FLAGS_kernel_overrides`` — so ``set_flags`` takes effect without any
invalidation hook. Because the predicate walk runs once per distinct
signature, the ``kernels.<name>.{picked,fallback}`` counters (metrics
registry, PR 4) count exactly one selection per compiled specialization —
the invariant the bench and tests pin (``kernels.moe.picked`` == compile
count). Each selection also emits a ``kernel_select`` run-log event that
``observability report`` renders as the kernel-selection section.

``FLAGS_kernel_overrides`` (e.g. ``"moe=dense,sdpa=xla"``) forces a named
implementation per kernel, bypassing availability — the operator escape
hatch when a kernel misbehaves on some shape or toolchain version.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..framework.flags import flag
from ..observability import metrics as _metrics
from ..observability import runlog as _runlog

__all__ = [
    "define_kernel", "register", "select", "dispatch", "kernels",
    "implementations", "kernel_table", "clear_cache", "KernelImpl",
    "WATCHED_FLAGS",
]


class KernelImpl:
    """One implementation of a kernel: ``fn`` plus its availability
    predicate (called with the exact dispatch arguments; ``None`` accepts
    unconditionally). ``fallback=True`` marks the always-safe composite —
    it sorts last and never consults a predicate."""

    __slots__ = ("name", "fn", "available", "fallback", "doc")

    def __init__(self, name: str, fn: Callable, available: Optional[Callable] = None,
                 fallback: bool = False, doc: str = ""):
        self.name = name
        self.fn = fn
        self.available = available
        self.fallback = bool(fallback)
        self.doc = doc

    def __repr__(self):
        return f"KernelImpl({self.name!r}{', fallback' if self.fallback else ''})"


class Kernel:
    __slots__ = ("name", "impls", "flags", "cache_key")

    def __init__(self, name: str, flags: Tuple[str, ...] = (), cache_key: Optional[Callable] = None):
        self.name = name
        self.impls: List[KernelImpl] = []
        self.flags = tuple(flags)
        self.cache_key = cache_key


_KERNELS: Dict[str, Kernel] = {}
_CACHE: Dict[tuple, KernelImpl] = {}

#: flags folded into EVERY kernel's selection-cache key (on top of the
#: per-kernel ``flags`` watch list): the SPMD pre-flight runs once per
#: compiled specialization, and kernel selection decides what gets compiled
#: — a pick cached under the old FLAGS_shard_check/FLAGS_hbm_budget_mb
#: values would skip the re-selection (and with it the fresh analyzer pass)
#: after ``set_flags`` toggles them.
WATCHED_FLAGS: Tuple[str, ...] = ("FLAGS_shard_check", "FLAGS_hbm_budget_mb")


def define_kernel(name: str, flags: Tuple[str, ...] = (), cache_key: Optional[Callable] = None) -> Kernel:
    """Declare kernel ``name``. ``flags`` lists flag names whose values
    feed the selection-cache key (a ``set_flags`` re-runs the predicates);
    ``cache_key`` is an optional callable contributing extra key material
    for module-level state flags can't see (e.g. interpret-mode toggles).
    Idempotent: re-defining keeps already-registered implementations."""
    k = _KERNELS.get(name)
    if k is None:
        k = _KERNELS[name] = Kernel(name, flags, cache_key)
    else:
        k.flags = tuple(flags)
        k.cache_key = cache_key
    _metrics.declare_counter(f"kernels.{name}.picked")
    _metrics.declare_counter(f"kernels.{name}.fallback")
    return k


def register(kernel: str, impl_name: str, fn: Optional[Callable] = None, *,
             available: Optional[Callable] = None, fallback: bool = False, doc: str = ""):
    """Register ``fn`` as implementation ``impl_name`` of ``kernel``
    (decorator form when ``fn`` is omitted). Implementations are tried in
    registration order with fallbacks sorted last; re-registering a name
    replaces it in place (reload-safe)."""

    def _do(f):
        k = _KERNELS.get(kernel) or define_kernel(kernel)
        impl = KernelImpl(impl_name, f, available, fallback, doc)
        for i, existing in enumerate(k.impls):
            if existing.name == impl_name:
                k.impls[i] = impl
                break
        else:
            k.impls.append(impl)
        k.impls.sort(key=lambda im: im.fallback)  # stable: fallbacks last
        clear_cache(kernel)
        return f

    return _do if fn is None else _do(fn)


def _abstract(v: Any):
    """Arrays (incl. tracers and Tensors) become (shape, dtype); anything
    else must already be hashable (static kwargs)."""
    if v is not None and hasattr(v, "shape") and hasattr(v, "dtype"):
        return ("array", tuple(int(d) for d in v.shape), str(v.dtype))
    return v


def _parse_overrides(s: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in (s or "").split(","):
        part = part.strip()
        if part and "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def select(kernel: str, *args, **kwargs) -> KernelImpl:
    """The implementation that will serve this call (cached per
    signature). Bumps ``kernels.<kernel>.picked``/``.fallback`` and emits
    a ``kernel_select`` run-log event exactly once per new signature."""
    k = _KERNELS[kernel]
    overrides = flag("FLAGS_kernel_overrides")
    key = (
        kernel,
        overrides,
        jax.default_backend(),
        tuple(flag(f) for f in k.flags),
        tuple(flag(f) for f in WATCHED_FLAGS),
        k.cache_key() if k.cache_key is not None else None,
        tuple(_abstract(a) for a in args),
        tuple(sorted((kw, _abstract(v)) for kw, v in kwargs.items())),
    )
    impl = _CACHE.get(key)
    if impl is not None:
        return impl
    forced = _parse_overrides(overrides).get(kernel)
    if forced is not None:
        for impl in k.impls:
            if impl.name == forced:
                break
        else:
            raise KeyError(
                f"FLAGS_kernel_overrides: kernel {kernel!r} has no implementation "
                f"{forced!r} (registered: {[im.name for im in k.impls]})")
    else:
        impl = None
        for cand in k.impls:
            if cand.fallback or cand.available is None or cand.available(*args, **kwargs):
                impl = cand
                break
        if impl is None:
            raise RuntimeError(
                f"kernel {kernel!r}: no implementation available for this call "
                "and no fallback registered")
    _CACHE[key] = impl
    _metrics.counter_inc(f"kernels.{kernel}." + ("fallback" if impl.fallback else "picked"))
    _runlog.emit("kernel_select", kernel=kernel, impl=impl.name,
                 fallback=impl.fallback, forced=forced is not None)
    return impl


def dispatch(kernel: str, *args, **kwargs):
    """Select (cached) and call the winning implementation."""
    return select(kernel, *args, **kwargs).fn(*args, **kwargs)


def kernels() -> List[str]:
    return sorted(_KERNELS)


def implementations(kernel: str) -> List[str]:
    return [im.name for im in _KERNELS[kernel].impls]


def kernel_table() -> List[dict]:
    """One row per (kernel, implementation) — the README registry table."""
    rows = []
    for name in sorted(_KERNELS):
        for im in _KERNELS[name].impls:
            rows.append({
                "kernel": name,
                "impl": im.name,
                "fallback": im.fallback,
                "flags": list(_KERNELS[name].flags),
                "doc": im.doc,
            })
    return rows


def clear_cache(kernel: Optional[str] = None) -> None:
    """Drop cached selections (all kernels, or just ``kernel``). Counters
    are NOT reset — a re-selection after an explicit clear counts again."""
    if kernel is None:
        _CACHE.clear()
        return
    for key in [key for key in _CACHE if key[0] == kernel]:
        del _CACHE[key]
