"""Flat-lane flash attention kernels: zero-relayout attention for the GPT
trunk.

Motivation (round-4 profile, v5 lite, b=8 s=1024 h=16 d=64): the classic
kernels in flash_attention.py take [b, h, s, d] operands, so XLA inserts
~6-9ms/step of relayout copies between the qkv projection (whose natural
output is [b, s, 3·h·d]) and every kernel call. These kernels instead read
the projection output's layout directly:

- Operands stay [b, s, H] (H = h·d) or packed [b, s, 3H]; BlockSpecs carve
  the lane (H) dimension into head-groups of hg·d lanes, and the kernel
  statically slices each head's d columns. No transposes anywhere in the
  attention path. hg is chosen by _head_group: the largest of {8,4,2,1,h}
  dividing h whose lane block is 128-aligned (or full-dimension) AND whose
  bwd dq accumulator (s·hg·d f32) stays within _DQ_ELEM_BUDGET — Mosaic
  compile time blows up past that.
- The backward is ONE fused kernel (grid over k-blocks, inner loop over
  q-blocks): s and dp computed once (5 MXU dots vs 7 for a split dq/dkv
  pair), one exp instead of two. dq accumulates in f32 in a VMEM-resident
  [s, hg·d] output block across the sequential k-block grid steps; dk/dv
  are per-block. Backward block_k is 256 to stay inside the ~16MB VMEM.
- lse/di live as [b, h//hg, s, hg] f32 so each head-group's stats are one
  full-lane block; the kernel selects a head's column with a one-hot
  multiply (dynamic lane slicing is not portable Mosaic).
- The softmax scale is folded into q (and k for the dq dot) tiles — 1/8th
  the VPU work of scaling the [block_q, block_k] logits tile; the causal
  mask (iota+compare+select) only runs on diagonal-intersecting tiles.

Parity anchor: same as flash_attention.py (fused_attention_op.cu).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# module-level so the autotune sweep (tpu_runbook.py sweep) can override;
# defaults chosen on v5 lite for the flagship shape
_BLOCK_Q = 512
_BLOCK_K_FWD = 512
_BLOCK_K_BWD = 256


def set_blocks(block_q=None, block_k_fwd=None, block_k_bwd=None):
    """Override kernel block sizes (autotune hook). Returns prior values."""
    global _BLOCK_Q, _BLOCK_K_FWD, _BLOCK_K_BWD
    prior = (_BLOCK_Q, _BLOCK_K_FWD, _BLOCK_K_BWD)
    if block_q:
        _BLOCK_Q = int(block_q)
    if block_k_fwd:
        _BLOCK_K_FWD = int(block_k_fwd)
    if block_k_bwd:
        _BLOCK_K_BWD = int(block_k_bwd)
    return prior
_MAX_SEQ = 2048
_INTERPRET = False  # run pallas_calls in interpreter mode (CPU parity tests)


def set_interpret(on: bool) -> bool:
    """Route the flat-kernel ``pl.pallas_call``s through the Pallas
    interpreter (CPU parity tests). Returns the prior setting."""
    global _INTERPRET
    prior = _INTERPRET
    _INTERPRET = bool(on)
    return prior
# Mosaic compile time blows up with the fused-bwd dq accumulator block
# (full-sequence [s, hg*d] f32, read-modify-write across k-steps): 1M elements
# did not compile in 20 min on-chip (2026-07-30); 512K compiles in seconds.
# The head-group size adapts so s*hg*d stays within this budget.
_DQ_ELEM_BUDGET = 512 * 1024


def _head_group(h, s, d, packed=False):
    # Largest divisor of h whose lane block is Mosaic-legal and whose bwd dq
    # accumulator fits the compile budget. A full-dimension (hg == h) lane
    # block is legal without 128-alignment ONLY for separate q/k/v operands —
    # in the packed [b, s, 3H] tensor an H-lane block sits at offsets H and 2H,
    # so it must be 128-aligned like any other block.
    for hg in range(min(h, 16), 0, -1):
        if h % hg != 0:
            continue
        aligned = (hg * d) % 128 == 0
        if (aligned or (hg == h and not packed)) and s * hg * d <= _DQ_ELEM_BUDGET:
            return hg
    return 0  # no viable grouping — enabled() rejects


def enabled(qkv_shape=None, packed=True) -> bool:
    """Gate for dispatch from flash_attention_qkv. On TPU backends only;
    FLAGS_flash_flat allows forcing the classic path. ``packed`` must match
    the wrapper being dispatched to (flash_packed vs flash_flat*)."""
    from ..framework.flags import flag

    if jax.default_backend() not in ("tpu", "axon") and not _INTERPRET:
        return False
    if not flag("FLAGS_flash_flat"):
        return False
    if qkv_shape is not None:
        b, s, three, h, d = qkv_shape
        block = min(_BLOCK_Q, s)
        if not (s >= 256 and s % block == 0 and s <= _MAX_SEQ and 64 <= d <= 128 and d % 8 == 0):
            return False
        if _head_group(h, s, d, packed=packed) == 0:
            return False
    return True


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())), preferred_element_type=jnp.float32)


_NT = ((1,), (1,))
_NN = ((1,), (0,))
_TN = ((0,), (0,))


# -- kernels ----------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, block_k, seq_len, scale, hg, d, has_bias):
    from jax.experimental import pallas as pl

    if has_bias:
        bias_ref, o_ref, lse_ref = rest  # bias [block_q, seq] additive, finite
    else:
        (o_ref, lse_ref), bias_ref = rest, None

    qi = pl.program_id(2)
    block_q = q_ref.shape[0]
    nkb = seq_len // block_k
    lse_cols = []
    for hi in range(hg):
        c0 = hi * d
        q = q_ref[:, c0:c0 + d] * jnp.asarray(scale, q_ref.dtype)
        m = jnp.full((block_q,), -jnp.inf, jnp.float32)
        l = jnp.zeros((block_q,), jnp.float32)
        acc = jnp.zeros((block_q, d), jnp.float32)

        def body(kb, carry, masked):
            m, l, acc = carry
            kt = k_ref[pl.dslice(kb * block_k, block_k), c0:c0 + d]
            vt = v_ref[pl.dslice(kb * block_k, block_k), c0:c0 + d]
            s = _dot(q, kt, _NT)  # scale pre-applied via q
            if has_bias:
                s = s + bias_ref[:, pl.dslice(kb * block_k, block_k)].astype(jnp.float32)
            if masked:
                qp = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                kp = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                s = jnp.where(qp >= kp, s, -jnp.inf)
            mn = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - mn[:, None])
            al = jnp.exp(m - mn)
            ln = al * l + jnp.sum(p, axis=-1)
            accn = acc * al[:, None] + _dot(p.astype(vt.dtype), vt, _NN)
            return mn, ln, accn

        if causal:
            n_full = (qi * block_q) // block_k  # strictly below the diagonal
            n_live = n_full + (block_q + block_k - 1) // block_k
            m, l, acc = jax.lax.fori_loop(0, n_full, lambda kb, c: body(kb, c, False), (m, l, acc))
            m, l, acc = jax.lax.fori_loop(n_full, n_live, lambda kb, c: body(kb, c, True), (m, l, acc))
        else:
            m, l, acc = jax.lax.fori_loop(0, nkb, lambda kb, c: body(kb, c, False), (m, l, acc))

        o_ref[:, c0:c0 + d] = (acc / l[:, None]).astype(o_ref.dtype)
        oh = (jax.lax.broadcasted_iota(jnp.int32, (1, hg), 1) == hi).astype(jnp.float32)
        lse_cols.append((m + jnp.log(l))[:, None] * oh)
    lse_ref[...] = sum(lse_cols)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, *refs,
                causal, block_q, block_k, seq_len, scale, hg, d, has_bias):
    from jax.experimental import pallas as pl

    if has_bias:
        bias_ref, dq_ref, dk_ref, dv_ref = refs  # bias [seq, block_k]
    else:
        (dq_ref, dk_ref, dv_ref), bias_ref = refs, None

    ki = pl.program_id(2)
    nq = seq_len // block_q
    for hi in range(hg):
        c0 = hi * d
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (1, hg), 1) == hi).astype(jnp.float32)
        k = k_ref[:, c0:c0 + d]
        v = v_ref[:, c0:c0 + d]
        ks = k * jnp.asarray(scale, k.dtype)
        dk = jnp.zeros((block_k, d), jnp.float32)
        dv = jnp.zeros((block_k, d), jnp.float32)

        def body(qb, carry, masked):
            dk, dv = carry
            sl = pl.dslice(qb * block_q, block_q)
            qt = q_ref[sl, c0:c0 + d] * jnp.asarray(scale, k.dtype)
            dot_ = do_ref[sl, c0:c0 + d]
            lse = jnp.sum(lse_ref[sl, :] * onehot, axis=1, keepdims=True)
            di = jnp.sum(di_ref[sl, :] * onehot, axis=1, keepdims=True)
            s = _dot(qt, k, _NT)  # scale pre-applied via qt
            if has_bias:
                s = s + bias_ref[sl, :].astype(jnp.float32)
            p = jnp.exp(s - lse)
            if masked:
                qp = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
                kp = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                p = jnp.where(qp >= kp, p, 0.0)
            pc = p.astype(dot_.dtype)
            dv = dv + _dot(pc, dot_, _TN)
            dp = _dot(dot_, v, _NT)
            ds = (p * (dp - di)).astype(k.dtype)
            dk = dk + _dot(ds, qt, _TN)       # scale carried by qt
            contrib = _dot(ds, ks, _NN)       # scale carried by ks
            prev = dq_ref[sl, c0:c0 + d]
            dq_ref[sl, c0:c0 + d] = jnp.where(ki == 0, contrib, prev + contrib)
            return dk, dv

        if causal:
            q_start = (ki * block_k) // block_q
            n_diag_end = ((ki + 1) * block_k + block_q - 1) // block_q
            dk, dv = jax.lax.fori_loop(q_start, jnp.minimum(n_diag_end, nq),
                                       lambda qb, c: body(qb, c, True), (dk, dv))
            dk, dv = jax.lax.fori_loop(n_diag_end, nq,
                                       lambda qb, c: body(qb, c, False), (dk, dv))
        else:
            dk, dv = jax.lax.fori_loop(0, nq, lambda qb, c: body(qb, c, False), (dk, dv))

        dk_ref[:, c0:c0 + d] = dk.astype(dk_ref.dtype)
        dv_ref[:, c0:c0 + d] = dv.astype(dv_ref.dtype)


# -- pallas_call wrappers ---------------------------------------------------
# Packed operands: qkv [b, s, 3H]; q/k/v column-block index g is offset by
# h//hg per tensor. Separate operands: three [b, s, H].


def _fwd_call(operands, b, s, h, d, dtype, causal, packed):
    from jax.experimental import pallas as pl

    hg = _head_group(h, s, d, packed=packed)
    if hg == 0:
        raise ValueError(f"flat flash kernels unsupported for h={h}, s={s}, d={d} "
                         f"(no head grouping within the compile budget); gate with enabled()")
    hd = hg * d
    G = h // hg  # column blocks per tensor
    block_q = min(_BLOCK_Q, s)
    block_k = min(_BLOCK_K_FWD, s)
    if s % block_q or s % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide s={s}; "
                         f"fix via set_blocks()")
    scale = 1.0 / (d ** 0.5)

    if packed:
        in_specs = [
            pl.BlockSpec((None, block_q, hd), lambda bi, gi, qi: (bi, qi, gi)),
            pl.BlockSpec((None, s, hd), lambda bi, gi, qi: (bi, 0, G + gi)),
            pl.BlockSpec((None, s, hd), lambda bi, gi, qi: (bi, 0, 2 * G + gi)),
        ]
    else:
        in_specs = [
            pl.BlockSpec((None, block_q, hd), lambda bi, gi, qi: (bi, qi, gi)),
            pl.BlockSpec((None, s, hd), lambda bi, gi, qi: (bi, 0, gi)),
            pl.BlockSpec((None, s, hd), lambda bi, gi, qi: (bi, 0, gi)),
        ]

    bias = None
    if len(operands) > (1 if packed else 3):
        *operands, bias = operands
        # additive bias [b, 1, s, s] (broadcast over heads); rows for this
        # q-block resident in VMEM
        in_specs.append(pl.BlockSpec((None, None, block_q, s), lambda bi, gi, qi: (bi, 0, qi, 0)))
    # packed mode: the q/k/v specs are three column-block views of the SAME
    # [b, s, 3H] tensor, so it must appear once per spec
    operands = tuple(operands) * 3 if packed else tuple(operands)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, block_k=block_k, seq_len=s,
                          scale=scale, hg=hg, d=d, has_bias=bias is not None),
        grid=(b, G, s // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, hd), lambda bi, gi, qi: (bi, qi, gi)),
            pl.BlockSpec((None, None, block_q, hg), lambda bi, gi, qi: (bi, gi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), dtype),
            jax.ShapeDtypeStruct((b, G, s, hg), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(*operands, *( [bias] if bias is not None else [] ))
    return out, lse


def _bwd_call(operands, b, s, h, d, dtype, o, lse, do, causal, packed):
    from jax.experimental import pallas as pl

    hg = _head_group(h, s, d, packed=packed)
    if hg == 0:
        raise ValueError(f"flat flash kernels unsupported for h={h}, s={s}, d={d} "
                         f"(no head grouping within the compile budget); gate with enabled()")
    hd = hg * d
    G = h // hg
    block_q = min(_BLOCK_Q, s)
    block_k = min(_BLOCK_K_BWD, s)
    if s % block_q or s % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide s={s}; "
                         f"fix via set_blocks()")
    scale = 1.0 / (d ** 0.5)

    # di = rowsum(dO ∘ O) reshaped to the [b, G, s, hg] stat layout
    di = jnp.sum(do.astype(jnp.float32).reshape(b, s, h, d)
                 * o.astype(jnp.float32).reshape(b, s, h, d), axis=-1)
    di = jnp.swapaxes(di.reshape(b, s, G, hg), 1, 2)  # [b, G, s, hg]

    fullH = lambda bi, gi, ki: (bi, 0, gi)
    blkH = lambda bi, gi, ki: (bi, ki, gi)
    stat = lambda bi, gi, ki: (bi, gi, 0, 0)
    if packed:
        qkv_specs = [
            pl.BlockSpec((None, s, hd), fullH),
            pl.BlockSpec((None, block_k, hd), lambda bi, gi, ki: (bi, ki, G + gi)),
            pl.BlockSpec((None, block_k, hd), lambda bi, gi, ki: (bi, ki, 2 * G + gi)),
        ]
    else:
        qkv_specs = [
            pl.BlockSpec((None, s, hd), fullH),
            pl.BlockSpec((None, block_k, hd), blkH),
            pl.BlockSpec((None, block_k, hd), blkH),
        ]

    bias = None
    if len(operands) > (1 if packed else 3):
        *operands, bias = operands
    operands = tuple(operands) * 3 if packed else tuple(operands)
    extra_specs = [
        pl.BlockSpec((None, s, hd), fullH),           # do
        pl.BlockSpec((None, None, s, hg), stat),      # lse
        pl.BlockSpec((None, None, s, hg), stat),      # di
    ]
    extra_ops = [do, lse, di]
    if bias is not None:
        # bias columns for this k-block, all q rows resident
        extra_specs.append(pl.BlockSpec((None, None, s, block_k), lambda bi, gi, ki: (bi, 0, 0, ki)))
        extra_ops.append(bias)

    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, block_q=block_q, block_k=block_k,
                          seq_len=s, scale=scale, hg=hg, d=d, has_bias=bias is not None),
        grid=(b, G, s // block_k),
        in_specs=qkv_specs + extra_specs,
        out_specs=[
            pl.BlockSpec((None, s, hd), fullH),           # dq (f32 accumulator)
            pl.BlockSpec((None, block_k, hd), blkH),
            pl.BlockSpec((None, block_k, hd), blkH),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s, h * d), jnp.float32),
            jax.ShapeDtypeStruct((b, s, h * d), dtype),
            jax.ShapeDtypeStruct((b, s, h * d), dtype),
        ],
        interpret=_INTERPRET,
    )(*operands, *extra_ops)
    return dq.astype(dtype), dk, dv


# -- custom-vjp entries -----------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _flat_packed(qkv, hd_shape, causal):
    b, s, _ = qkv.shape
    h, d = hd_shape
    out, _ = _fwd_call((qkv,), b, s, h, d, qkv.dtype, causal, packed=True)
    return out


def _flat_packed_fwd(qkv, hd_shape, causal):
    b, s, _ = qkv.shape
    h, d = hd_shape
    out, lse = _fwd_call((qkv,), b, s, h, d, qkv.dtype, causal, packed=True)
    return out, (qkv, out, lse)


def _flat_packed_bwd(hd_shape, causal, res, g):
    qkv, o, lse = res
    b, s, _ = qkv.shape
    h, d = hd_shape
    dq, dk, dv = _bwd_call((qkv,), b, s, h, d, qkv.dtype, o, lse, g, causal, packed=True)
    return (jnp.concatenate([dq, dk, dv], axis=-1),)


_flat_packed.defvjp(_flat_packed_fwd, _flat_packed_bwd)


def flash_packed(qkv, causal=False):
    """qkv: [b, s, 3, h, d] (or [b, s, 3H] with heads given) — returns
    [b, s, h, d] to match flash_attention_qkv's contract."""
    b, s, three, h, d = qkv.shape
    flat = qkv.reshape(b, s, 3 * h * d)  # no-op relayout: d is already minor
    out = _flat_packed(flat, (h, d), causal)
    return out.reshape(b, s, h, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flat(q, k, v, hd_shape, causal):
    b, s, _ = q.shape
    h, d = hd_shape
    out, _ = _fwd_call((q, k, v), b, s, h, d, q.dtype, causal, packed=False)
    return out


def _flat_fwd(q, k, v, hd_shape, causal):
    b, s, _ = q.shape
    h, d = hd_shape
    out, lse = _fwd_call((q, k, v), b, s, h, d, q.dtype, causal, packed=False)
    return out, (q, k, v, out, lse)


def _flat_bwd(hd_shape, causal, res, g):
    q, k, v, o, lse = res
    b, s, _ = q.shape
    h, d = hd_shape
    return _bwd_call((q, k, v), b, s, h, d, q.dtype, o, lse, g, causal, packed=False)


_flat.defvjp(_flat_fwd, _flat_bwd)


def flash_flat(q, k, v, causal=False):
    """q/k/v: [b, s, h, d]; flat-lane kernel path, returns [b, s, h, d]."""
    b, s, h, d = q.shape
    out = _flat(q.reshape(b, s, h * d), k.reshape(b, s, h * d), v.reshape(b, s, h * d),
                (h, d), causal)
    return out.reshape(b, s, h, d)


# -- masked / GQA envelope (reference fused_attention_op.cu attn_mask path,
#    fused_softmax_mask.cu.h) -------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flat_masked(q, k, v, bias, hd_shape, causal):
    b, s, _ = q.shape
    h, d = hd_shape
    out, _ = _fwd_call((q, k, v, bias), b, s, h, d, q.dtype, causal, packed=False)
    return out


def _flat_masked_fwd(q, k, v, bias, hd_shape, causal):
    b, s, _ = q.shape
    h, d = hd_shape
    out, lse = _fwd_call((q, k, v, bias), b, s, h, d, q.dtype, causal, packed=False)
    return out, (q, k, v, bias, out, lse)


def _flat_masked_bwd(hd_shape, causal, res, g):
    q, k, v, bias, o, lse = res
    b, s, _ = q.shape
    h, d = hd_shape
    dq, dk, dv = _bwd_call((q, k, v, bias), b, s, h, d, q.dtype, o, lse, g, causal, packed=False)
    return dq, dk, dv, jnp.zeros_like(bias)  # masks are non-trainable inputs


_flat_masked.defvjp(_flat_masked_fwd, _flat_masked_bwd)


def mask_supported(b, s, h, d, mask_shape) -> bool:
    """Additive [b|1, 1, s, s] masks with FINITE entries (use -1e30, not
    -inf); full-row mask residency bounds s."""
    if s > 1024:
        return False
    ms = tuple(mask_shape)
    return len(ms) == 4 and ms[1] == 1 and ms[2] == s and ms[3] == s and ms[0] in (1, b)


def flash_flat_masked(q, k, v, mask, causal=False):
    """Masked attention through the flat kernels. ``mask``: additive bias
    [b|1, 1, s, s] (bool masks must be converted to 0/-1e30 by the caller).
    Grads flow to q/k/v; the mask gets zeros (non-trainable)."""
    b, s, h, d = q.shape
    if mask.shape[0] == 1 and b > 1:
        mask = jnp.broadcast_to(mask, (b,) + mask.shape[1:])
    out = _flat_masked(q.reshape(b, s, h * d), k.reshape(b, s, h * d),
                       v.reshape(b, s, h * d), mask, (h, d), causal)
    return out.reshape(b, s, h, d)


def flash_flat_gqa(q, k, v, causal=False, mask=None):
    """Grouped/multi-query attention: k/v have h_kv heads with h % h_kv == 0.
    KV heads are expanded to the query head count before the kernel (one
    bandwidth-bound repeat; the kernels then run the standard path) — the
    envelope contract of the reference's GQA-capable fused attention."""
    b, s, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv != 0:
        raise ValueError(f"GQA needs h_kv | h; got h={h}, h_kv={h_kv}")
    r = h // h_kv
    if r > 1:
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)
    if mask is not None:
        return flash_flat_masked(q, k, v, mask, causal)
    return flash_flat(q, k, v, causal)
