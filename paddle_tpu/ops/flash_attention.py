"""Flash attention: Pallas TPU kernel + jnp fallback.

Parity target: the reference's fused attention CUDA path
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_softmax_mask.cu.h). TPU-first: an online-softmax blocked kernel that
streams K/V tiles through VMEM, fp32 accumulation, MXU-shaped 128-wide tiles.
Backward uses recompute (jax.custom_vjp with the jnp reference bwd) — flat
memory like flash-attention-2.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK_Q = 256
_BLOCK_K = 256


def flash_attention_available(q_shape, k_shape=None) -> bool:
    """Kernel path needs TPU + tile-friendly shapes (seq multiple of the
    block size) + self-attention-like q/k lengths (the kernel derives K/V
    tiling from q's seq_len)."""
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    if len(q_shape) != 4:
        return False
    b, s, h, d = q_shape
    if k_shape is not None and tuple(k_shape) != tuple(q_shape):
        return False
    return s % _BLOCK_Q == 0 and s >= _BLOCK_Q and d >= 64 and d % 8 == 0


def _reference_attention(q, k, v, causal):
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        s = logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, block_k, seq_len, scale):
    from jax.experimental import pallas as pl

    q = q_ref[...].astype(jnp.float32) * scale  # [block_q, d]
    block_q = q.shape[0]
    qi = pl.program_id(2)

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    n_kblocks = seq_len // block_k
    if causal:
        n_kblocks_live = (qi * block_q) // block_k + (block_q + block_k - 1) // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_tile = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_tile = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = q @ k_tile.T  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + p @ v_tile
        return m_new, l_new, acc_new

    if causal:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks_live, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m, l, acc))

    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal):
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    block_q = min(_BLOCK_Q, s)
    block_k = min(_BLOCK_K, s)
    scale = 1.0 / (d**0.5)

    # layout: [b, h, s, d] for contiguous per-head tiles
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    grid = (b, h, s // block_q)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, block_k=block_k, seq_len=s, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, causal):
    return _flash_fwd(q, k, v, causal)


def _flash_vjp_fwd(q, k, v, causal):
    out = _flash_fwd(q, k, v, causal)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, res, g):
    q, k, v = res
    # recompute-based backward via the reference path (XLA fuses it well);
    # a hand-written Pallas bwd kernel is a round-2+ perf item.
    _, vjp = jax.vjp(lambda a, b, c: _reference_attention(a, b, c, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _jax_library_flash(q, k, v, causal):
    """JAX's in-tree Pallas TPU flash kernels (fwd AND bwd are flash —
    flat-memory backward, unlike our recompute-reference bwd)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention as _fa,
    )

    b, s, h, d = q.shape
    blk = min(512, s)
    sizes = BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk, block_k_dkv=blk, block_q_dkv=blk,
        block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk,
    )
    out = _fa(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
              causal=causal, sm_scale=1.0 / (d ** 0.5), block_sizes=sizes)
    return jnp.swapaxes(out, 1, 2)


def flash_attention(q, k, v, causal=False, impl="auto"):
    """q/k/v: [batch, seq, heads, head_dim]; returns same layout.

    ``impl``: 'auto' prefers the jax library Pallas kernel pair (flash
    backward); 'own' forces this module's kernel (flash fwd, recompute bwd).
    Genuine input errors (shape mismatches) propagate; only a missing/older
    library API falls back.
    """
    if tuple(k.shape) != tuple(q.shape) or tuple(v.shape) != tuple(q.shape):
        raise ValueError(
            f"flash_attention requires equal q/k/v shapes (self-attention); got "
            f"q{tuple(q.shape)} k{tuple(k.shape)} v{tuple(v.shape)} — use "
            "scaled_dot_product_attention for cross-length attention")
    s = q.shape[1]
    lib_ok = impl != "own" and s % min(512, s) == 0
    if lib_ok:
        try:
            return _jax_library_flash(q, k, v, causal)
        except (ImportError, AttributeError, TypeError):  # jax API drift only
            pass
    return _flash(q, k, v, causal)
