"""Flash attention: full Pallas TPU kernel pair (fwd + bwd) + jnp fallback.

Parity target: the reference's fused attention CUDA path
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_softmax_mask.cu.h). TPU-first: an online-softmax blocked kernel that
streams K/V tiles through VMEM, fp32 accumulation, MXU-shaped tiles.

The backward is a hand-written flash-attention-2 style kernel pair
(dq kernel + dk/dv kernel) over compact [b, h, s] f32 logsumexp/di
residuals. The jax library kernels (pallas/ops/tpu/flash_attention.py)
broadcast their per-row stats to [b, h, s, 128] and [b, h, s, block_k]
f32 tensors in HBM before every backward call — profiled at >20ms/step on
the flagship bench; these kernels keep the stats 1-D and recompute p
tiles in VMEM, which is what makes the fused step ~1.25x faster.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK_Q = 512
_BLOCK_K = 512
_MAX_SEQ_VMEM = 4096  # whole-K/V-in-VMEM streaming bound
_INTERPRET = False  # run pallas_calls in interpreter mode (CPU parity tests)


def set_interpret(on: bool) -> bool:
    """Route every ``pl.pallas_call`` here through the Pallas interpreter —
    the CPU path tier-1 uses to test the kernel math against
    :func:`_reference_attention` without a TPU. Returns the prior setting."""
    global _INTERPRET
    prior = _INTERPRET
    _INTERPRET = bool(on)
    return prior


def flash_attention_available(q_shape, k_shape=None) -> bool:
    """Kernel path needs TPU (or interpreter mode, for CPU parity runs) +
    tile-friendly shapes (seq multiple of the block size) +
    self-attention-like q/k lengths (the kernel derives K/V tiling from
    q's seq_len)."""
    if jax.default_backend() not in ("tpu", "axon") and not _INTERPRET:
        return False
    if len(q_shape) != 4:
        return False
    b, s, h, d = q_shape
    if k_shape is not None and tuple(k_shape) != tuple(q_shape):
        return False
    # seq must be an exact multiple of the tile the kernels will pick
    # (min(_BLOCK_Q, s)) or rows/keys beyond grid*block are silently dropped
    block = min(_BLOCK_Q, s)
    return s >= 256 and s % block == 0 and s <= _MAX_SEQ_VMEM and d >= 64 and d % 8 == 0


def _reference_attention(q, k, v, causal):
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        s = logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# -- forward kernel ---------------------------------------------------------


def _dot32(a, b, dims):
    """Matmul in the input dtype (bf16 hits the MXU at full rate) with f32
    accumulation — the casts-to-f32-first form runs the MXU at 1/4 rate."""
    return jax.lax.dot_general(a, b, (dims, ((), ())), preferred_element_type=jnp.float32)


_NT = ((1,), (1,))  # contract last dim of both (a @ b.T)
_NN = ((1,), (0,))  # a @ b
_TN = ((0,), (0,))  # a.T @ b


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k, seq_len, scale):
    from jax.experimental import pallas as pl

    q = q_ref[...]  # [block_q, d], input dtype
    block_q = q.shape[0]
    qi = pl.program_id(2)

    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    n_kblocks = seq_len // block_k
    if causal:
        n_kblocks_live = (qi * block_q) // block_k + (block_q + block_k - 1) // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_tile = k_ref[pl.dslice(kb * block_k, block_k), :]
        v_tile = v_ref[pl.dslice(kb * block_k, block_k), :]
        s = _dot32(q, k_tile, _NT) * scale  # [block_q, block_k] f32
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + _dot32(p.astype(v_tile.dtype), v_tile, _NN)
        return m_new, l_new, acc_new

    if causal:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks_live, body, (m, l, acc))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m, l, acc))

    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[...] = (m + jnp.log(l))[:, None]


def _flash_fwd(q, k, v, causal):
    """Returns (out, lse) with out [b,s,h,d] and lse [b,h,s] f32 (in
    scale-applied logit units)."""
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    block_q = min(_BLOCK_Q, s)
    block_k = min(_BLOCK_K, s)
    scale = 1.0 / (d**0.5)

    # layout: [b, h, s, d] for contiguous per-head tiles
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    grid = (b, h, s // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, block_k=block_k, seq_len=s, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2), lse


# -- backward kernels -------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref, *, causal, block_k, seq_len, scale):
    """dQ = (P ∘ (dO Vᵀ − di)) K · scale, streamed over K/V tiles."""
    from jax.experimental import pallas as pl

    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[...]  # [block_q, 1]
    di = di_ref[...]
    block_q = q.shape[0]
    qi = pl.program_id(2)

    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    n_kblocks = seq_len // block_k
    if causal:
        n_kblocks = (qi * block_q) // block_k + (block_q + block_k - 1) // block_k

    def body(kb, acc):
        k_tile = k_ref[pl.dslice(kb * block_k, block_k), :]
        v_tile = v_ref[pl.dslice(kb * block_k, block_k), :]
        s = _dot32(q, k_tile, _NT) * scale  # scaled logits [block_q, block_k]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = _dot32(do, v_tile, _NT)  # [block_q, block_k]
        ds = (p * (dp - di)).astype(k_tile.dtype)
        return acc + _dot32(ds, k_tile, _NN)

    acc = jax.lax.fori_loop(0, n_kblocks, body, acc)
    dq_ref[...] = (acc * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dk_ref, dv_ref, *, causal, block_q, seq_len, scale):
    """dV = Pᵀ dO;  dK = (P ∘ (dO Vᵀ − di))ᵀ Q · scale, streamed over Q tiles."""
    from jax.experimental import pallas as pl

    k = k_ref[...]
    v = v_ref[...]
    block_k = k.shape[0]
    ki = pl.program_id(2)

    dk = jnp.zeros((block_k, k.shape[1]), jnp.float32)
    dv = jnp.zeros((block_k, k.shape[1]), jnp.float32)
    n_qblocks = seq_len // block_q
    q_start = (ki * block_k) // block_q if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q_tile = q_ref[pl.dslice(qb * block_q, block_q), :]
        do_tile = do_ref[pl.dslice(qb * block_q, block_q), :]
        lse = lse_ref[pl.dslice(qb * block_q, block_q), :]  # [block_q, 1]
        di = di_ref[pl.dslice(qb * block_q, block_q), :]
        s = _dot32(q_tile, k, _NT) * scale  # [block_q, block_k]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        pc = p.astype(do_tile.dtype)
        dv = dv + _dot32(pc, do_tile, _TN)
        dp = _dot32(do_tile, v, _NT)
        ds = (p * (dp - di)).astype(q_tile.dtype)
        dk = dk + _dot32(ds, q_tile, _TN)
        return dk, dv

    dk, dv = jax.lax.fori_loop(q_start, n_qblocks, body, (dk, dv))
    dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, causal):
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    block_q = min(_BLOCK_Q, s)
    block_k = min(_BLOCK_K, s)
    scale = 1.0 / (d**0.5)

    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    dot = jnp.swapaxes(do, 1, 2)
    ot = jnp.swapaxes(o, 1, 2)
    # di = rowsum(dO ∘ O) [b, h, s, 1] — a cheap fused reduction, f32
    di = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1, keepdims=True)

    row_specs = [
        pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((None, None, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, block_k=block_k, seq_len=s, scale=scale),
        grid=(b, h, s // block_q),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((None, None, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=_INTERPRET,
    )(qt, kt, vt, dot, lse, di)

    col_specs = [
        pl.BlockSpec((None, None, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
        pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        pl.BlockSpec((None, None, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
        pl.BlockSpec((None, None, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        pl.BlockSpec((None, None, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, block_q=block_q, seq_len=s, scale=scale),
        grid=(b, h, s // block_k),
        in_specs=col_specs,
        out_specs=[
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((None, None, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        interpret=_INTERPRET,
    )(qt, kt, vt, dot, lse, di)

    back = lambda x: jnp.swapaxes(x, 1, 2)
    return back(dq), back(dk), back(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, causal):
    out, _ = _flash_fwd(q, k, v, causal)
    return out


def _flash_vjp_fwd(q, k, v, causal):
    out, lse = _flash_fwd(q, k, v, causal)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _jax_library_flash(q, k, v, causal):
    """JAX's in-tree Pallas TPU flash kernels. Kept for comparison/debug
    (impl='lib') — its backward materializes [b,h,s,128]/[b,h,s,block_k]
    f32 stat broadcasts in HBM, measured slower than the in-repo pair."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention as _fa,
    )

    b, s, h, d = q.shape
    blk = min(512, s)
    sizes = BlockSizes(
        block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
        block_q_major_dkv=blk, block_k_major_dkv=blk, block_k_dkv=blk, block_q_dkv=blk,
        block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk,
    )
    out = _fa(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2),
              causal=causal, sm_scale=1.0 / (d ** 0.5), block_sizes=sizes)
    return jnp.swapaxes(out, 1, 2)


def flash_attention(q, k, v, causal=False, impl="auto"):
    """q/k/v: [batch, seq, heads, head_dim]; returns same layout.

    ``impl``: 'auto'/'own' use this module's kernel pair (flash fwd + flash
    bwd over compact lse/di residuals); 'lib' forces the jax library kernels.
    Genuine input errors (shape mismatches) propagate; only a missing/older
    library API falls back.
    """
    if tuple(k.shape) != tuple(q.shape) or tuple(v.shape) != tuple(q.shape):
        raise ValueError(
            f"flash_attention requires equal q/k/v shapes (self-attention); got "
            f"q{tuple(q.shape)} k{tuple(k.shape)} v{tuple(v.shape)} — use "
            "scaled_dot_product_attention for cross-length attention")
    if impl == "lib":
        try:
            return _jax_library_flash(q, k, v, causal)
        except (ImportError, AttributeError, TypeError):  # jax API drift only
            pass
    return _flash(q, k, v, causal)


def flash_attention_qkv(qkv, causal=False):
    """Packed-projection form: ``qkv`` is [batch, seq, 3, heads, head_dim]
    (the qkv-matmul output reshaped, un-sliced). Dispatches to the packed
    flat-lane kernels (flash_attention_flat) when enabled, else slices and
    uses the classic kernel pair."""
    if qkv.ndim != 5 or qkv.shape[2] != 3:
        raise ValueError(f"flash_attention_qkv expects [b, s, 3, h, d]; got {tuple(qkv.shape)}")
    from . import flash_attention_flat as _flat

    if _flat.enabled(qkv.shape):
        return _flat.flash_packed(qkv, causal)
    return _flash(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal)
