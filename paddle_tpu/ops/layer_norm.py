"""Fused LayerNorm with a hand-derived backward.

Parity: the reference's layer_norm CUDA kernels
(paddle/phi/kernels/gpu/layer_norm_kernel.cu fwd + layer_norm_grad_kernel).

Why not autodiff: the r4 profile of the flagship step shows XLA's
autodiff-of-(mean/var/normalize) backward compiling into ~0.7ms/layer of
multiply_reduce fusions (~19ms/step over 32 LNs) — several times the
bandwidth bound. The closed-form backward

    x̂   = (x − μ) σ⁻¹
    g    = dy ⊙ w
    dx   = σ⁻¹ (g − mean(g) − x̂ ⊙ mean(g ⊙ x̂))
    dw   = Σ_tokens dy ⊙ x̂,   db = Σ_tokens dy

is two token-row reductions + one elementwise pass, which XLA fuses into a
couple of kernels. Statistics are computed and applied in f32 regardless of
input dtype (bf16-safe); residuals are (x, μ, σ⁻¹) — recompute-x̂-in-bwd, no
[.., d] normalized tensor stored.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm_fused(x, w, b, eps=1e-5):
    y, _ = _ln_fwd_core(x, w, b, eps)
    return y


def _ln_fwd_core(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = (xhat * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)
    return y, (x, mu, rstd)


def _ln_vjp_fwd(x, w, b, eps):
    y, res = _ln_fwd_core(x, w, b, eps)
    return y, res + (w,)


def _ln_vjp_bwd(eps, res, dy):
    x, mu, rstd, w = res
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mu) * rstd
    g = dyf * w.astype(jnp.float32)
    mg = jnp.mean(g, axis=-1, keepdims=True)
    mgx = jnp.mean(g * xhat, axis=-1, keepdims=True)
    dx = (rstd * (g - mg - xhat * mgx)).astype(x.dtype)
    red = tuple(range(dy.ndim - 1))
    dw = jnp.sum(dyf * xhat, axis=red).astype(w.dtype)
    db = jnp.sum(dyf, axis=red).astype(w.dtype)
    return dx, dw, db


layer_norm_fused.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)
