"""Distribution base classes.

Paddle parity: python/paddle/distribution/distribution.py (Distribution base)
and exponential_family.py. TPU-first design: distributions are pure-functional
over jax.numpy; sampling draws explicit PRNG keys from the framework RNG
(traced-safe under jit via rng_scope), entropy/log_prob are jittable.
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..framework.core import Tensor, _wrap_value, unwrap


def _arr(x, dtype=None):
    v = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
    if dtype is not None and v.dtype != dtype:
        v = v.astype(dtype)
    return v


def _param(x, dtype=jnp.float32):
    """Keep Tensors (tape-connected); lift raw values to constant Tensors."""
    if isinstance(x, Tensor):
        return x
    arr = jnp.asarray(x, dtype) if isinstance(x, (int, float)) else jnp.asarray(x)
    t = Tensor.__new__(Tensor)
    t._init(arr, stop_gradient=True)
    return t


class Distribution:
    """Base of all distributions (ref distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..tensor.math import exp

        return exp(self.log_prob(value))  # tape-connected: grads flow to params

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape: Sequence[int]):
        return tuple(sample_shape) + self._batch_shape + self._event_shape


class ExponentialFamily(Distribution):
    """Exponential-family base enabling Bregman-divergence KL
    (ref exponential_family.py; KL via jax.grad replaces the reference's
    double-backward over natural parameters)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError
