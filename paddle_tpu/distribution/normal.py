"""Normal, Uniform distributions (ref python/paddle/distribution/{normal,uniform}.py).

All math routes through :func:`paddle_tpu.framework.core.primitive` so that
log_prob / rsample / entropy are differentiable w.r.t. Tensor parameters on
the eager tape (the reference's distributions differentiate through dygraph
ops the same way).
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import random as jrandom

from ..framework.core import Tensor, _wrap_value, primitive, unwrap
from ..framework.random import split_key
from .distribution import Distribution, ExponentialFamily, _param


class Normal(ExponentialFamily):
    """N(loc, scale) — ref normal.py:32."""

    def __init__(self, loc, scale, name=None):
        self._loc = _param(loc)
        self._scale = _param(scale)
        shape = jnp.broadcast_shapes(unwrap(self._loc).shape, unwrap(self._scale).shape)
        super().__init__(batch_shape=shape)

    # raw-array views used by closed-form KL formulas
    @property
    def loc(self):
        return jnp.broadcast_to(unwrap(self._loc), self.batch_shape)

    @property
    def scale(self):
        return jnp.broadcast_to(unwrap(self._scale), self.batch_shape)

    @property
    def mean(self):
        return primitive(lambda l, s: jnp.broadcast_to(l, jnp.broadcast_shapes(l.shape, s.shape)), self._loc, self._scale, _name="normal_mean")

    @property
    def variance(self):
        return primitive(lambda l, s: jnp.broadcast_to(s**2, jnp.broadcast_shapes(l.shape, s.shape)), self._loc, self._scale, _name="normal_variance")

    @property
    def stddev(self):
        return primitive(lambda l, s: jnp.broadcast_to(s, jnp.broadcast_shapes(l.shape, s.shape)), self._loc, self._scale, _name="normal_stddev")

    def sample(self, shape=(), seed=0):
        with_noise = self.rsample(shape)
        return with_noise.detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        eps = jrandom.normal(split_key(), out_shape, jnp.result_type(unwrap(self._loc).dtype, jnp.float32))
        return primitive(lambda l, s: l + s * eps, self._loc, self._scale, _name="normal_rsample")

    def log_prob(self, value):
        value = _param(value)

        def impl(l, s, v):
            return -((v - l) ** 2) / (2 * s**2) - jnp.log(s) - 0.5 * math.log(2 * math.pi)

        return primitive(impl, self._loc, self._scale, value, _name="normal_log_prob")

    def entropy(self):
        def impl(l, s):
            shape = jnp.broadcast_shapes(l.shape, s.shape)
            return jnp.broadcast_to(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), shape)

        return primitive(impl, self._loc, self._scale, _name="normal_entropy")

    def probs(self, value):
        return self.prob(value)

    @property
    def _natural_parameters(self):
        loc, scale = self.loc, self.scale
        return (loc / scale**2, -0.5 / scale**2)

    def _log_normalizer(self, x, y):
        return -0.25 * x**2 / y + 0.5 * jnp.log(-math.pi / y)

    @property
    def _mean_carrier_measure(self):
        return 0.0


class Uniform(Distribution):
    """U[low, high) — ref uniform.py:34."""

    def __init__(self, low, high, name=None):
        self._low = _param(low)
        self._high = _param(high)
        shape = jnp.broadcast_shapes(unwrap(self._low).shape, unwrap(self._high).shape)
        super().__init__(batch_shape=shape)

    @property
    def low(self):
        return jnp.broadcast_to(unwrap(self._low), self.batch_shape)

    @property
    def high(self):
        return jnp.broadcast_to(unwrap(self._high), self.batch_shape)

    @property
    def mean(self):
        return primitive(lambda a, b: (a + b) / 2, self._low, self._high, _name="uniform_mean")

    @property
    def variance(self):
        return primitive(lambda a, b: (b - a) ** 2 / 12, self._low, self._high, _name="uniform_variance")

    def sample(self, shape=(), seed=0):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        u = jrandom.uniform(split_key(), out_shape, jnp.result_type(unwrap(self._low).dtype, jnp.float32))
        return primitive(lambda a, b: a + (b - a) * u, self._low, self._high, _name="uniform_rsample")

    def log_prob(self, value):
        value = _param(value)

        def impl(a, b, v):
            inside = (v >= a) & (v < b)
            return jnp.where(inside, -jnp.log(b - a), -jnp.inf)

        return primitive(impl, self._low, self._high, value, _name="uniform_log_prob")

    def entropy(self):
        return primitive(lambda a, b: jnp.log(b - a), self._low, self._high, _name="uniform_entropy")
