"""Independent + TransformedDistribution
(ref python/paddle/distribution/{independent,transformed_distribution}.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import _wrap_value, unwrap
from .distribution import Distribution, _arr
from .transform import ChainTransform, Transform, _sum_rightmost


class Independent(Distribution):
    """Reinterpret rightmost batch dims as event dims (ref independent.py:22)."""

    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        if reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds base batch rank")
        self._base = base
        self._rank = int(reinterpreted_batch_rank)
        shape = base.batch_shape + base.event_shape
        n_batch = len(base.batch_shape) - self._rank
        super().__init__(batch_shape=shape[:n_batch], event_shape=shape[n_batch:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        from ..framework.core import primitive

        lp = self._base.log_prob(value)
        return primitive(lambda a: _sum_rightmost(a, self._rank), lp, _name="independent_log_prob")

    def entropy(self):
        from ..framework.core import primitive

        ent = self._base.entropy()
        return primitive(lambda a: _sum_rightmost(a, self._rank), ent, _name="independent_entropy")


class TransformedDistribution(Distribution):
    """Pushforward of ``base`` through ``transforms`` (ref transformed_distribution.py:22)."""

    def __init__(self, base: Distribution, transforms):
        self._base = base
        self._transforms = [transforms] if isinstance(transforms, Transform) else list(transforms)
        chain = ChainTransform(self._transforms)
        shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(shape)
        event_rank = max(chain._event_dim, len(base.event_shape))
        cut = len(out_shape) - event_rank
        super().__init__(batch_shape=out_shape[:cut], event_shape=out_shape[cut:])
        self._chain = chain

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        from ..framework.core import primitive

        x = self._base.rsample(shape)
        return primitive(self._chain._forward, x, _name="transformed_rsample")

    def log_prob(self, value):
        from ..framework.core import primitive
        from .distribution import _param

        event_rank = max(self._chain._event_dim, len(self._base.event_shape))

        # every stage is a tape op, so grads flow to the value AND the base
        # distribution's parameters (normalizing-flow training path)
        y = _param(value)
        x = self._chain.inverse(y)
        lp_base = self._base.log_prob(x)
        ldj = self._chain.forward_log_det_jacobian(x)
        k_lp = event_rank - len(self._base.event_shape)
        k_ldj = event_rank - self._chain._event_dim
        lp = primitive(lambda a: _sum_rightmost(a, k_lp), lp_base, _name="transformed_lp_sum")
        ldj_s = primitive(lambda a: _sum_rightmost(a, k_ldj), ldj, _name="transformed_ldj_sum")
        return primitive(lambda a, b: a - b, lp, ldj_s, _name="transformed_log_prob")
