"""paddle.distribution parity (ref python/paddle/distribution/__init__.py)."""
from .beta import Beta, Dirichlet  # noqa: F401
from .categorical import Categorical, Multinomial  # noqa: F401
from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401
from .normal import Normal, Uniform  # noqa: F401
from .transform import (  # noqa: F401
    AbsTransform,
    AffineTransform,
    ChainTransform,
    ExpTransform,
    IndependentTransform,
    PowerTransform,
    ReshapeTransform,
    SigmoidTransform,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
)
from .transformed_distribution import Independent, TransformedDistribution  # noqa: F401

__all__ = [
    "Beta",
    "Categorical",
    "Dirichlet",
    "Distribution",
    "ExponentialFamily",
    "Multinomial",
    "Normal",
    "Uniform",
    "kl_divergence",
    "register_kl",
    "Independent",
    "TransformedDistribution",
    "Transform",
    "AbsTransform",
    "AffineTransform",
    "ChainTransform",
    "ExpTransform",
    "IndependentTransform",
    "PowerTransform",
    "ReshapeTransform",
    "SigmoidTransform",
    "SoftmaxTransform",
    "StackTransform",
    "StickBreakingTransform",
    "TanhTransform",
]
