"""KL divergence registry + closed forms (ref python/paddle/distribution/kl.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammaln

from ..framework.core import _wrap_value
from .beta import Beta, Dirichlet
from .categorical import Categorical
from .distribution import Distribution, ExponentialFamily
from .normal import Normal, Uniform

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL fn (ref kl.py:64)."""

    def decorator(f):
        _REGISTRY[(cls_p, cls_q)] = f
        return f

    return decorator


def _lookup(tp, tq):
    best, best_score = None, None
    for (cp, cq), f in _REGISTRY.items():
        if issubclass(tp, cp) and issubclass(tq, cq):
            score = (len(tp.__mro__) - tp.__mro__.index(cp)) + (
                len(tq.__mro__) - tq.__mro__.index(cq)
            )
            if best_score is None or score > best_score:
                best, best_score = f, score
    return best


def kl_divergence(p: Distribution, q: Distribution):
    """KL(p || q) via registry dispatch (ref kl.py:32)."""
    f = _lookup(type(p), type(q))
    if f is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})"
        )
    return f(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    from ..framework.core import primitive

    def impl(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))

    return primitive(impl, p._loc, p._scale, q._loc, q._scale, _name="kl_normal_normal")


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    from ..framework.core import primitive

    def impl(pl, ph, ql, qh):
        result = jnp.log((qh - ql) / (ph - pl))
        outside = (ql > pl) | (qh < ph)
        return jnp.where(outside, jnp.inf, result)

    return primitive(impl, p._low, p._high, q._low, q._high, _name="kl_uniform_uniform")


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    from ..framework.core import primitive

    def impl(pw, qw):
        plog = jnp.log(pw / jnp.sum(pw, -1, keepdims=True))
        qlog = jnp.log(qw / jnp.sum(qw, -1, keepdims=True))
        return jnp.sum(jnp.exp(plog) * (plog - qlog), -1)

    return primitive(impl, p._logits, q._logits, _name="kl_categorical_categorical")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    from ..framework.core import primitive

    def impl(pa, pb, qa, qb):
        return (
            betaln(qa, qb)
            - betaln(pa, pb)
            + (pa - qa) * digamma(pa)
            + (pb - qb) * digamma(pb)
            + (qa - pa + qb - pb) * digamma(pa + pb)
        )

    return primitive(impl, p._alpha, p._beta, q._alpha, q._beta, _name="kl_beta_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    from ..framework.core import primitive

    def impl(a, b):
        a0 = jnp.sum(a, -1)
        return (
            gammaln(a0)
            - jnp.sum(gammaln(a), -1)
            - gammaln(jnp.sum(b, -1))
            + jnp.sum(gammaln(b), -1)
            + jnp.sum((a - b) * (digamma(a) - digamma(a0)[..., None]), -1)
        )

    return primitive(impl, p._concentration, q._concentration, _name="kl_dirichlet_dirichlet")


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """Bregman-divergence KL over natural params (ref kl.py:172).

    The reference differentiates the log-normalizer with double-backward;
    here jax.value_and_grad does it directly.
    """
    if type(p) is not type(q):
        raise NotImplementedError("expfamily KL requires identical families")
    p_nat = [jnp.asarray(t) for t in p._natural_parameters]
    q_nat = [jnp.asarray(t) for t in q._natural_parameters]

    # grad of the SUMMED log-normalizer is elementwise in the natural params,
    # so the Bregman divergence below stays per-batch-element
    grads = jax.grad(
        lambda *ts: jnp.sum(p._log_normalizer(*ts)), argnums=tuple(range(len(p_nat)))
    )(*p_nat)
    kl = q._log_normalizer(*q_nat) - p._log_normalizer(*p_nat)
    for pn, qn, g in zip(p_nat, q_nat, grads):
        kl = kl - (qn - pn) * g
    return _wrap_value(kl)
