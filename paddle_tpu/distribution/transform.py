"""Bijective transforms (ref python/paddle/distribution/transform.py).

TPU-first: forward/inverse/log-det are pure jnp functions; where the
reference hand-derives log-det Jacobians we keep the same closed forms
(they're already elementwise/cheap) rather than calling jax.jacfwd.
"""
from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp

from ..framework.core import Tensor, _wrap_value, unwrap
from .distribution import _arr

__all__ = [
    "Transform",
    "AbsTransform",
    "AffineTransform",
    "ChainTransform",
    "ExpTransform",
    "IndependentTransform",
    "PowerTransform",
    "ReshapeTransform",
    "SigmoidTransform",
    "SoftmaxTransform",
    "StackTransform",
    "StickBreakingTransform",
    "TanhTransform",
]


def _sum_rightmost(x, n):
    return jnp.sum(x, axis=tuple(range(-n, 0))) if n > 0 else x


class Transform:
    """Base transform (ref transform.py:50)."""

    _event_dim = 0

    @classmethod
    def _is_injective(cls):
        return True

    def __call__(self, x):
        from .transformed_distribution import TransformedDistribution
        from .distribution import Distribution

        if isinstance(x, Distribution):
            return TransformedDistribution(x, [self])
        if isinstance(x, Transform):
            return ChainTransform([self, x])
        return self.forward(x)

    def forward(self, x):
        from ..framework.core import primitive
        from .distribution import _param

        return primitive(self._forward, _param(x), _name=f"{type(self).__name__}.forward")

    def inverse(self, y):
        from ..framework.core import primitive
        from .distribution import _param

        return primitive(self._inverse, _param(y), _name=f"{type(self).__name__}.inverse")

    def forward_log_det_jacobian(self, x):
        from ..framework.core import primitive
        from .distribution import _param

        return primitive(
            self._forward_log_det_jacobian, _param(x), _name=f"{type(self).__name__}.fldj"
        )

    def inverse_log_det_jacobian(self, y):
        from ..framework.core import primitive
        from .distribution import _param

        return primitive(
            lambda v: -self._forward_log_det_jacobian(self._inverse(v)),
            _param(y),
            _name=f"{type(self).__name__}.ildj",
        )

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    @classmethod
    def _is_injective(cls):
        return False

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch, matching reference's (-y, y) simplification

    def inverse_log_det_jacobian(self, y):
        return _wrap_value(jnp.zeros_like(_arr(y)))


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return 1 / (1 + jnp.exp(-x))

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        from jax.nn import softplus

        return -softplus(-x) - softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        from jax.nn import softplus

        return 2.0 * (math.log(2.0) - x - softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _event_dim = 1

    @classmethod
    def _is_injective(cls):
        return False

    def _forward(self, x):
        z = jnp.exp(x - jnp.max(x, -1, keepdims=True))
        return z / jnp.sum(z, -1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)


class ChainTransform(Transform):
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)
        self._event_dim = max((t._event_dim for t in self.transforms), default=0)

    def _is_injective(self):
        return all(t._is_injective() for t in self.transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        value = 0.0
        event_dim = self._event_dim
        for t in self.transforms:
            value = value + _sum_rightmost(
                t._forward_log_det_jacobian(x), event_dim - t._event_dim
            )
            x = t._forward(x)
        return value

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class IndependentTransform(Transform):
    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._event_dim = base._event_dim + self.reinterpreted_batch_rank

    def _is_injective(self):
        return self.base._is_injective()

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        return _sum_rightmost(
            self.base._forward_log_det_jacobian(x), self.reinterpreted_batch_rank
        )


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._event_dim = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.reshape(x, batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return jnp.reshape(y, batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n]) + self.out_event_shape if n else tuple(shape) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:-n]) + self.in_event_shape if n else tuple(shape) + self.in_event_shape


class StackTransform(Transform):
    """Apply a list of transforms along slices of ``axis`` (ref transform.py)."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, fn_name, x):
        slices = jnp.moveaxis(x, self.axis, 0)
        outs = [getattr(t, fn_name)(s) for t, s in zip(self.transforms, slices)]
        return jnp.moveaxis(jnp.stack(outs, 0), 0, self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)


class StickBreakingTransform(Transform):
    """Unconstrained R^k -> simplex of dim k+1 (ref transform.py)."""

    _event_dim = 1

    def _forward(self, x):
        from jax.nn import sigmoid

        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = sigmoid(x - jnp.log(offset))
        zcum = jnp.cumprod(1 - z, -1)
        head = z * jnp.concatenate([jnp.ones_like(z[..., :1]), zcum[..., :-1]], -1)
        tail = zcum[..., -1:]
        return jnp.concatenate([head, tail], -1)

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - jnp.concatenate([jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], -1)
        z = y[..., :-1] / rem
        k = z.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        from jax.nn import log_sigmoid

        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        t = x - jnp.log(offset)
        y = self._forward(x)
        ycum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - jnp.concatenate([jnp.zeros_like(ycum[..., :1]), ycum[..., :-1]], -1)
        return jnp.sum(jnp.log(rem) + log_sigmoid(t) + log_sigmoid(-t), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)
