"""Beta, Dirichlet (ref python/paddle/distribution/{beta,dirichlet}.py)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import random as jrandom
from jax.scipy.special import betaln, digamma, gammaln

from ..framework.core import _wrap_value
from ..framework.random import split_key
from .distribution import ExponentialFamily, _arr


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration) — ref dirichlet.py:22."""

    def __init__(self, concentration):
        from .distribution import _param

        self._concentration = _param(concentration)
        self.concentration = _arr(concentration, jnp.float32)
        super().__init__(
            batch_shape=self.concentration.shape[:-1],
            event_shape=self.concentration.shape[-1:],
        )

    @property
    def mean(self):
        from ..framework.core import primitive

        return primitive(
            lambda a: a / jnp.sum(a, -1, keepdims=True), self._concentration, _name="dirichlet_mean"
        )

    @property
    def variance(self):
        from ..framework.core import primitive

        def impl(a):
            a0 = jnp.sum(a, -1, keepdims=True)
            m = a / a0
            return m * (1 - m) / (a0 + 1)

        return primitive(impl, self._concentration, _name="dirichlet_variance")

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape + self.event_shape
        g = jrandom.gamma(split_key(), jnp.broadcast_to(self.concentration, shape))
        return _wrap_value(g / jnp.sum(g, -1, keepdims=True))

    def log_prob(self, value):
        from ..framework.core import primitive
        from .distribution import _param

        def impl(a, v):
            return (
                jnp.sum((a - 1) * jnp.log(v), -1)
                + gammaln(jnp.sum(a, -1))
                - jnp.sum(gammaln(a), -1)
            )

        return primitive(impl, self._concentration, _param(value), _name="dirichlet_log_prob")

    def entropy(self):
        from ..framework.core import primitive

        k = self.concentration.shape[-1]

        def impl(a):
            a0 = jnp.sum(a, -1)
            lnB = jnp.sum(gammaln(a), -1) - gammaln(a0)
            return lnB + (a0 - k) * digamma(a0) - jnp.sum((a - 1) * digamma(a), -1)

        return primitive(impl, self._concentration, _name="dirichlet_entropy")


class Beta(ExponentialFamily):
    """Beta(alpha, beta) — ref beta.py:22; implemented over Dirichlet like the reference."""

    def __init__(self, alpha, beta):
        from .distribution import _param

        self._alpha = _param(alpha)
        self._beta = _param(beta)
        self.alpha = _arr(alpha, jnp.float32)
        self.beta = _arr(beta, jnp.float32)
        self.alpha, self.beta = jnp.broadcast_arrays(self.alpha, self.beta)
        self._dirichlet = Dirichlet(jnp.stack([self.alpha, self.beta], -1))
        super().__init__(batch_shape=self.alpha.shape)

    @property
    def mean(self):
        from ..framework.core import primitive

        return primitive(lambda a, b: a / (a + b), self._alpha, self._beta, _name="beta_mean")

    @property
    def variance(self):
        from ..framework.core import primitive

        def impl(a, b):
            s = a + b
            return a * b / (s**2 * (s + 1))

        return primitive(impl, self._alpha, self._beta, _name="beta_variance")

    def sample(self, shape=()):
        from ..framework.core import unwrap

        return _wrap_value(unwrap(self._dirichlet.sample(shape))[..., 0])

    def log_prob(self, value):
        from ..framework.core import primitive
        from .distribution import _param

        def impl(a, b, v):
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - betaln(a, b)

        return primitive(impl, self._alpha, self._beta, _param(value), _name="beta_log_prob")

    def entropy(self):
        from ..framework.core import primitive

        def impl(a, b):
            return (
                betaln(a, b)
                - (a - 1) * digamma(a)
                - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b)
            )

        return primitive(impl, self._alpha, self._beta, _name="beta_entropy")
