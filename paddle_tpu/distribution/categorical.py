"""Categorical, Multinomial (ref python/paddle/distribution/{categorical,multinomial}.py)."""
from __future__ import annotations

import jax.numpy as jnp
from jax import random as jrandom

from ..framework.core import _wrap_value, unwrap
from ..framework.random import split_key
from .distribution import Distribution, _arr


class Categorical(Distribution):
    """Categorical over unnormalized ``logits`` (the reference takes logits
    meaning unnormalized probabilities — ref categorical.py:30)."""

    def __init__(self, logits, name=None):
        from .distribution import _param

        # reference semantics: `logits` are non-negative relative weights;
        # single source of truth — views below derive from it on demand
        self._logits = _param(logits)
        super().__init__(batch_shape=tuple(_arr(self._logits).shape[:-1]))

    @property
    def logits(self):
        return _arr(self._logits, jnp.float32)

    @property
    def _log_p(self):
        w = self.logits
        return jnp.log(w / jnp.sum(w, -1, keepdims=True))

    @property
    def probs_all(self):
        return jnp.exp(self._log_p)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        idx = jrandom.categorical(split_key(), self._log_p, shape=shape)
        from ..framework.dtype import to_jax_dtype

        # int64 parity policy applied uniformly with argmax/argsort
        return _wrap_value(idx.astype(to_jax_dtype("int64")))

    @staticmethod
    def _gather(table, v):
        t = jnp.broadcast_to(table, v.shape + table.shape[-1:])
        return jnp.take_along_axis(t, v[..., None], -1)[..., 0]

    def log_prob(self, value):
        from ..framework.core import primitive

        v = _arr(value).astype(jnp.int32)

        def impl(w):
            log_p = jnp.log(w / jnp.sum(w, -1, keepdims=True))
            return self._gather(log_p, v)

        return primitive(impl, self._logits, _name="categorical_log_prob")

    def probs(self, value):
        from ..framework.core import primitive

        v = _arr(value).astype(jnp.int32)

        def impl(w):
            p = w / jnp.sum(w, -1, keepdims=True)
            return self._gather(p, v)

        return primitive(impl, self._logits, _name="categorical_probs")

    def entropy(self):
        from ..framework.core import primitive

        def impl(w):
            log_p = jnp.log(w / jnp.sum(w, -1, keepdims=True))
            return -jnp.sum(jnp.exp(log_p) * log_p, -1)

        return primitive(impl, self._logits, _name="categorical_entropy")


class Multinomial(Distribution):
    """Multinomial(total_count, probs) — ref multinomial.py:25."""

    def __init__(self, total_count: int, probs):
        self.total_count = int(total_count)
        self.probs = _arr(probs, jnp.float32)
        self.probs = self.probs / jnp.sum(self.probs, -1, keepdims=True)
        super().__init__(batch_shape=self.probs.shape[:-1], event_shape=self.probs.shape[-1:])

    @property
    def mean(self):
        return _wrap_value(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap_value(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        logits = jnp.log(self.probs)
        draws = jrandom.categorical(
            split_key(), logits, shape=(self.total_count,) + shape
        )
        k = self.probs.shape[-1]
        one_hot = jnp.sum(jnp.eye(k, dtype=self.probs.dtype)[draws], axis=0)
        return _wrap_value(one_hot)

    def log_prob(self, value):
        v = _arr(value, self.probs.dtype)
        from jax.scipy.special import gammaln

        logits = jnp.log(self.probs)
        # mask 0 * log(0) = 0 * -inf for zero-count zero-probability categories
        term = jnp.where((v == 0) & jnp.isinf(logits), 0.0, v * logits)
        return _wrap_value(
            gammaln(jnp.asarray(self.total_count + 1.0))
            - jnp.sum(gammaln(v + 1.0), -1)
            + jnp.sum(term, -1)
        )

    def entropy(self):
        # no closed form; Monte-Carlo-free bound not in reference either —
        # match reference by computing over support only for small counts
        raise NotImplementedError("Multinomial entropy has no closed form")
