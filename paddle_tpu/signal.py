"""paddle_tpu.signal — STFT/ISTFT and framing.

Parity: ``paddle.signal`` (reference python/paddle/signal.py: frame,
overlap_add, stft, istft over the frame/overlap_add ops in
paddle/fluid/operators/{frame_op,overlap_add_op}.cc). TPU-first: framing is a
gather (XLA fuses it), FFTs are XLA FFT HLOs, everything rides ``primitive``
for autograd/jit/static.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .tensor._helpers import Tensor, ensure_tensor, op, unwrap


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slice overlapping frames: [..., seq] -> [..., frame_length, n_frames]
    (axis=-1) or [seq, ...] -> [n_frames, frame_length, ...] (axis=0)."""
    if axis not in (0, -1):
        raise ValueError("frame: axis must be 0 or -1")

    def fn(v):
        seq = v.shape[axis]
        n_frames = 1 + (seq - frame_length) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        offs = jnp.arange(frame_length)
        if axis == -1:
            idx = starts[None, :] + offs[:, None]          # [frame_length, n_frames]
            return jnp.take(v, idx, axis=-1)
        idx = starts[:, None] + offs[None, :]              # [n_frames, frame_length]
        return jnp.take(v, idx, axis=0)

    return op(fn, ensure_tensor(x), _name="frame")


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of frame: overlap-add frames back into a signal."""
    if axis not in (0, -1):
        raise ValueError("overlap_add: axis must be 0 or -1")

    def fn(v):
        if axis == -1:
            frame_length, n_frames = v.shape[-2], v.shape[-1]
            seq = (n_frames - 1) * hop_length + frame_length
            starts = jnp.arange(n_frames) * hop_length
            idx = starts[None, :] + jnp.arange(frame_length)[:, None]  # [fl, nf]
            out = jnp.zeros(v.shape[:-2] + (seq,), v.dtype)
            return out.at[..., idx].add(v)
        n_frames, frame_length = v.shape[0], v.shape[1]
        seq = (n_frames - 1) * hop_length + frame_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]      # [nf, fl]
        out = jnp.zeros((seq,) + v.shape[2:], v.dtype)
        return out.at[idx].add(v)

    return op(fn, ensure_tensor(x), _name="overlap_add")


def _window_array(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    w = unwrap(window) if isinstance(window, Tensor) else jnp.asarray(window)
    if w.shape != (n_fft,):
        raise ValueError(f"window must have shape ({n_fft},), got {tuple(w.shape)}")
    return w.astype(dtype)


def stft(x, n_fft: int, hop_length: Optional[int] = None, win_length: Optional[int] = None,
         window=None, center: bool = True, pad_mode: str = "reflect",
         normalized: bool = False, onesided: bool = True, name=None):
    """[batch, seq] (or [seq]) -> [batch, n_fft//2+1 or n_fft, n_frames]
    complex spectrogram (reference signal.py:stft semantics)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    aux = [ensure_tensor(window)] if window is not None else []

    def fn(v, *w):
        real_dtype = v.dtype if jnp.issubdtype(v.dtype, jnp.floating) else jnp.float32
        win = _window_array(w[0] if w else None, win_length, real_dtype)
        if win_length < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if center:
            v = jnp.pad(v, [(0, 0), (n_fft // 2, n_fft // 2)], mode=pad_mode)
        seq = v.shape[-1]
        n_frames = 1 + (seq - n_fft) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]   # [nf, n_fft]
        frames = v[:, idx] * win[None, None, :]              # [b, nf, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        spec = jnp.swapaxes(spec, -1, -2)                    # [b, freq, nf]
        return spec[0] if squeeze else spec

    return op(fn, ensure_tensor(x), *aux, _name="stft")


def istft(x, n_fft: int, hop_length: Optional[int] = None, win_length: Optional[int] = None,
          window=None, center: bool = True, normalized: bool = False,
          onesided: bool = True, length: Optional[int] = None, return_complex: bool = False, name=None):
    """Inverse STFT with window-envelope normalization (reference istft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    aux = [ensure_tensor(window)] if window is not None else []

    def fn(spec, *w):
        win = _window_array(w[0] if w else None, win_length, jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        spec = jnp.swapaxes(spec, -1, -2)                    # [b, nf, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided else jnp.fft.ifft(spec, axis=-1).real
        frames = frames * win[None, None, :]
        n_frames = frames.shape[1]
        seq = (n_frames - 1) * hop_length + n_fft
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        sig = jnp.zeros((frames.shape[0], seq), frames.dtype).at[:, idx].add(frames)
        env = jnp.zeros((seq,), frames.dtype).at[idx.reshape(-1)].add(
            jnp.tile(win * win, n_frames))
        sig = sig / jnp.maximum(env, 1e-11)[None, :]
        if center:
            sig = sig[:, n_fft // 2: seq - n_fft // 2]
        if length is not None:
            sig = sig[:, :length]
        return sig[0] if squeeze else sig

    return op(fn, ensure_tensor(x), *aux, _name="istft")
