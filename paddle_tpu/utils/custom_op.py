"""Custom-op seam: host-implemented (numpy) ops inside eager, jit and static
graphs.

Parity: the reference's custom-operator machinery —
paddle/fluid/framework/custom_operator.cc (dlopen'd kernels registered into
the op registry) and python/paddle/utils/cpp_extension/cpp_extension.py
(build+load). TPU-first: a compiled XLA program cannot call into arbitrary
user code on-device, so the seam is ``jax.pure_callback`` — the op becomes
an opaque host-callback node in the XLA graph (PJRT handles the
device↔host transfers) — paired with ``jax.custom_vjp`` so a user-supplied
backward participates in autodiff under eager tape, ``jax.grad``, jit
TrainStep and static Executor programs alike.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _as_spec_tree(spec):
    """Normalize to a tuple of ShapeDtypeStruct."""
    if isinstance(spec, jax.ShapeDtypeStruct):
        return (spec,)
    return tuple(spec)


def make_callback_op(forward: Callable, backward: Optional[Callable] = None,
                     infer_spec: Optional[Callable] = None, name: str = "custom_op"):
    """Build a raw-array op from numpy-level ``forward``/``backward``.

    - ``forward(*np_arrays) -> np array | tuple`` runs on the host.
    - ``backward(*np_inputs, *np_outputs, *np_out_grads) -> grad per input``
      (the reference py_func backward contract, custom_operator.cc grad-op
      ordering). Omit it for a non-differentiable op.
    - ``infer_spec(*ShapeDtypeStruct) -> ShapeDtypeStruct | tuple`` gives
      output shapes; defaults to "same as first input".

    The result is a plain jnp-level function: usable directly, under
    ``jax.jit``/``jax.grad``, and through :func:`paddle_tpu.tensor._helpers.op`
    on Tensors.
    """
    if infer_spec is None:
        infer_spec = lambda *xs: jax.ShapeDtypeStruct(xs[0].shape, xs[0].dtype)

    def _call_fwd(*xs):
        specs = infer_spec(*(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs))
        multi = not isinstance(specs, jax.ShapeDtypeStruct)
        out = jax.pure_callback(
            lambda *a: jax.tree_util.tree_map(np.asarray, forward(*a)),
            specs, *xs, vmap_method="sequential")
        return out, multi

    if backward is None:
        def fn(*xs):
            out, _ = _call_fwd(*xs)
            return out
        fn.__name__ = name
        return fn

    @jax.custom_vjp
    def fn(*xs):
        out, _ = _call_fwd(*xs)
        return out

    def fn_fwd(*xs):
        out, multi = _call_fwd(*xs)
        outs = tuple(out) if multi else (out,)
        return out, (xs, outs)

    def fn_bwd(res, g):
        xs, outs = res
        gs = tuple(g) if isinstance(g, (tuple, list)) else (g,)
        in_specs = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs)
        if len(in_specs) == 1:
            in_specs = in_specs[0]
        grads = jax.pure_callback(
            lambda *a: jax.tree_util.tree_map(np.asarray, backward(*a)),
            in_specs, *xs, *outs, *gs, vmap_method="sequential")
        return tuple(grads) if isinstance(grads, (tuple, list)) else (grads,)

    fn.defvjp(fn_fwd, fn_bwd)
    return fn


class CustomOp:
    """Tensor-level custom op (the ``paddle.utils.cpp_extension.load`` stand-in:
    returns a callable module-like object whose ``__call__`` works on
    paddle_tpu Tensors in every execution mode)."""

    def __init__(self, forward, backward=None, infer_spec=None, name="custom_op"):
        self._raw = make_callback_op(forward, backward, infer_spec, name)
        self.name = name

    def raw(self, *arrays):
        """jnp-level form (for use inside other raw-array code)."""
        return self._raw(*arrays)

    def __call__(self, *tensors):
        from ..tensor._helpers import ensure_tensor, op

        return op(self._raw, *[ensure_tensor(t) for t in tensors], _name=self.name)


def load(name: str, forward=None, backward=None, infer_spec=None, **unused_build_kwargs):
    """API-compatible stand-in for ``paddle.utils.cpp_extension.load``: the
    reference compiles C++/CUDA sources and dlopens them
    (cpp_extension.py:464); here the kernel body is a Python/numpy callable
    running as a host callback. Build-system kwargs (sources, extra_cflags,
    ...) are accepted and ignored."""
    if forward is None:
        raise ValueError(
            "paddle_tpu custom ops are host callbacks: pass forward= (and "
            "optionally backward=, infer_spec=) instead of C++ sources")
    return CustomOp(forward, backward, infer_spec, name=name)
