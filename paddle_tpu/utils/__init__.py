from . import unique_name  # noqa: F401
from . import custom_op as cpp_extension  # noqa: F401 — host-callback stand-in
from .custom_op import CustomOp, make_callback_op  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def deprecated(update_to="", since="", reason="", level=0):
    """Deprecation decorator (reference utils/deprecated.py)."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*a, **k):
            msg = f"API {fn.__name__} is deprecated since {since}: {reason}"
            if update_to:
                msg += f"; use {update_to} instead"
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **k)

        return inner

    return wrap


def run_check():
    """Smoke-check the install (reference utils/install_check.py run_check):
    one tiny train step on the default backend."""
    import numpy as np

    import paddle_tpu as paddle

    m = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = (m(x) ** 2).mean()
    loss.backward()
    opt.step()
    import jax

    print(f"paddle_tpu is installed successfully! backend: {jax.default_backend()}, "
          f"devices: {len(jax.devices())}")


def require_version(min_version, max_version=None):
    """Version gate (reference utils/op_version.py require_version)."""
    import paddle_tpu

    def key(v):  # zero-pad to 3 components so "0.3" == "0.3.0"
        parts = [int(p) for p in str(v).split(".")[:3] if p.isdigit()]
        return tuple(parts + [0] * (3 - len(parts)))

    cur = key(paddle_tpu.__version__)
    if key(min_version) > cur or (max_version and key(max_version) < cur):
        raise RuntimeError(
            f"version {paddle_tpu.__version__} outside [{min_version}, {max_version}]")
    return True
