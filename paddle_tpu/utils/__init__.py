from . import unique_name  # noqa: F401
from . import custom_op as cpp_extension  # noqa: F401 — host-callback stand-in
from .custom_op import CustomOp, make_callback_op  # noqa: F401


def try_import(name):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
