"""Unique-name generator (parity: python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import itertools
from collections import defaultdict

_counters = defaultdict(itertools.count)


def generate(key="tmp"):
    return f"{key}_{next(_counters[key])}"


def guard(new_generator=None):
    import contextlib

    return contextlib.nullcontext()
