"""AMP (parity: python/paddle/amp — auto_cast + GradScaler).

TPU-first: bfloat16 is the native mixed-precision dtype; it shares float32's
exponent range so loss scaling is unnecessary — for bf16 construct
``GradScaler(enable=False)`` (a pass-through, and what ``decorate`` implies).
For fp16, ``GradScaler`` implements the reference's REAL dynamic loss
scaling (python/paddle/amp/grad_scaler.py:26 + check_finite_and_unscale op,
per Micikevicius et al. 2018): grow the scale every ``incr_every_n_steps``
clean steps, back it off after ``decr_every_n_nan_or_inf`` overflowed steps,
and skip the optimizer update on overflow. The found-inf flag comes from ONE
fused on-device all-nonfinite reduction over the unscaled grads (a single
host sync per step, not per tensor), and every transition is visible through
the observability spine: ``amp.loss_scale`` gauge, ``amp.skipped_steps``
counter, ``loss_scale`` run-log events.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, _wrap_value
from ..framework.dtype import to_jax_dtype

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate", "amp_state"]


class _AmpState(threading.local):
    enabled = False
    dtype = "bfloat16"
    level = "O1"
    custom_white_list = None
    custom_black_list = None


_STATE = _AmpState()

# Ops safe to run in low precision (parity: the C++ AMP lists in
# paddle/fluid/imperative/amp_auto_cast.cc). On TPU the list only matters for
# the eager path; under jit, `decorate`-style param casting + XLA do the rest.
WHITE_LIST = {"matmul", "linear", "conv1d", "conv2d", "conv3d", "bmm", "mm", "einsum", "flash_attention", "sdpa"}
BLACK_LIST = {"exp", "log", "log2", "log10", "mean", "sum", "softmax", "log_softmax", "cross_entropy", "layer_norm", "batch_norm", "norm", "logsumexp", "cumsum"}


def amp_state():
    return _STATE


def _install_hook():
    from ..framework import core as _core

    def hook(op_name, vals):
        if not _STATE.enabled:
            return vals
        return maybe_cast_inputs(op_name, vals)

    _core._amp_hook = hook


_install_hook()


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1", dtype="bfloat16"):
    prev = (_STATE.enabled, _STATE.dtype, _STATE.level, _STATE.custom_white_list, _STATE.custom_black_list)
    _STATE.enabled = enable
    _STATE.dtype = dtype
    _STATE.level = level
    _STATE.custom_white_list = set(custom_white_list) if custom_white_list else None
    _STATE.custom_black_list = set(custom_black_list) if custom_black_list else None
    try:
        yield
    finally:
        (_STATE.enabled, _STATE.dtype, _STATE.level, _STATE.custom_white_list, _STATE.custom_black_list) = prev


amp_guard = auto_cast


def maybe_cast_inputs(op_name, vals):
    """Called by the eager dispatcher: cast float inputs per AMP lists."""
    if not _STATE.enabled:
        return vals
    white = WHITE_LIST | (_STATE.custom_white_list or set())
    black = BLACK_LIST | (_STATE.custom_black_list or set())
    dt = to_jax_dtype(_STATE.dtype)
    if op_name in white:
        return [v.astype(dt) if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != dt else v for v in vals]
    if op_name in black:
        return [v.astype(jnp.float32) if hasattr(v, "dtype") and v.dtype == dt else v for v in vals]
    # unlisted ops run in the incoming dtype (paddle O1 gray-list semantics)
    return vals


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16", master_weight=None, save_dtype=None):
    """O2: cast model params to the compute dtype (master weights stay fp32
    in the optimizer state on the jit path)."""
    if models is not None:
        ms = models if isinstance(models, (list, tuple)) else [models]
        for m in ms:
            m.astype(dtype)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (parity: python/paddle/amp/grad_scaler.py:26).
    Pass ``enable=False`` for bf16 (no scaling needed); functional for fp16."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = init_loss_scaling
        self._incr_ratio, self._decr_ratio = incr_ratio, decr_ratio
        self._incr_every, self._decr_every = incr_every_n_steps, decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        self._host_step = 0
        if enable:
            from ..observability.metrics import gauge_set

            gauge_set("amp.loss_scale", float(self._scale))

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if self._unscaled:
            raise RuntimeError("unscale_() has already been called on this optimizer since the last update()")
        inv = 1.0 / self._scale
        # one fused any-nonfinite reduction on device, ONE host sync at the
        # end — the per-parameter bool() loop synced the pipeline per tensor
        flags = []
        for p in optimizer._params:
            if p.grad is not None:
                g = p.grad._value * inv
                flags.append(jnp.any(~jnp.isfinite(g)))
                p.grad._value = g
        self._found_inf = bool(jnp.any(jnp.stack(flags))) if flags else False
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        self._host_step += 1
        if not self._found_inf:
            optimizer.step()
        else:
            from ..observability import runlog
            from ..observability.metrics import counter_inc

            counter_inc("amp.skipped_steps")
            runlog.emit("bad_step", step=self._host_step, component="amp",
                        loss_scale=float(self._scale))
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        self._unscaled = False
        if not (self._enable and self._dynamic):
            return
        prev, reason = self._scale, None
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
                reason = "backoff"
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
                reason = "grow"
        if reason is not None:
            from ..observability import runlog
            from ..observability.metrics import gauge_set

            gauge_set("amp.loss_scale", float(self._scale))
            runlog.emit("loss_scale", step=self._host_step, reason=reason,
                        value=float(self._scale), prev=float(prev))

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def set_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state["good_steps"]
        self._bad_steps = state["bad_steps"]
