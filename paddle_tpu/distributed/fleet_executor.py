"""FleetExecutor: actor-model interceptor DAG runtime.

Parity anchors: paddle/fluid/distributed/fleet_executor/ — ``Carrier``
(carrier.h:49) owns ``Interceptor``s (interceptor.h:46; compute / amplifier /
source / sink variants) exchanging ``InterceptorMessage`` over in-process
queues or a brpc MessageBus; the task graph is ``TaskNode`` (task_node.h).
The reference uses it for distributed inference and static pipeline serving.

TPU-native design: training-time pipelining is compiled (spmd_pipeline —
ppermute inside one XLA program), so this runtime targets what the reference
actually used the DAG for: HOST-side streaming through model partitions —
serving pipelines where stages (tokenize → predictor shard → detokenize)
overlap across in-flight requests. Interceptors are threads; edges are the
native bounded channels (csrc/channel.h) — the same byte-channel the C++
data feed uses, so backpressure is real (a full channel blocks the producer),
and payloads cross stages as pickled messages exactly like the reference's
protobuf InterceptorMessage.
"""
from __future__ import annotations

import pickle
import threading
from typing import Callable, Dict, List, Optional

from ..framework.native import Channel

_DATA, _STOP = 0, 1


class TaskNode:
    """One node of the DAG (reference task_node.h). ``role`` is informative
    ('source'/'compute'/'sink'/'amplifier'); ``fn`` maps payload → payload
    for compute nodes, payload → list[payload] for amplifiers."""

    def __init__(self, fn: Optional[Callable] = None, role: str = "compute",
                 task_id: Optional[int] = None, max_run_times: int = 1, name: str = ""):
        self.fn = fn
        self.role = role
        self.task_id = task_id
        self.max_run_times = max_run_times
        self.name = name or role
        self.downstream: List["TaskNode"] = []

    def add_downstream_task(self, node: "TaskNode"):
        self.downstream.append(node)
        return node


class _Interceptor(threading.Thread):
    """One actor: drains its inbox channel, applies the node fn, forwards to
    the outbox (reference interceptor.h Compute/Amplifier interceptors).
    A STOP message (carrying the count of messages sent) flows through and
    shuts the chain down in order."""

    def __init__(self, node: TaskNode, inbox: Channel, outbox: Optional[Channel],
                 errors: list):
        super().__init__(daemon=True, name=f"interceptor-{node.name}")
        self.node = node
        self.inbox = inbox
        self.outbox = outbox
        self.errors = errors

    def run(self):
        try:
            while True:
                raw = self.inbox.get()
                if raw is None:  # channel closed
                    break
                kind, seq, payload = pickle.loads(raw)
                if kind == _STOP:
                    if self.outbox is not None:
                        self.outbox.put(raw)
                    break
                outs = [payload]
                if self.node.fn is not None:
                    out = self.node.fn(payload)
                    outs = list(out) if self.node.role == "amplifier" else [out]
                if self.outbox is not None:
                    for j, o in enumerate(outs):
                        self.outbox.put(pickle.dumps((_DATA, (seq, j), o)))
        except Exception as e:  # surfaced by Carrier.wait
            self.errors.append((self.node.name, e))  # noqa: PTA305 (fault ledger of a bounded carrier run, drained when the run ends)
            if self.outbox is not None:
                self.outbox.put(pickle.dumps((_STOP, -1, None)))


class Carrier:
    """Owns the interceptors of one (linear or fan-out-free) task chain and
    the channels between them (reference carrier.h). ``run`` feeds payloads
    in, returns outputs in order."""

    def __init__(self, chain: List[TaskNode], capacity: int = 8):
        self.chain = chain
        self.capacity = capacity

    def run(self, feeds) -> list:
        channels = [Channel(self.capacity) for _ in range(len(self.chain) + 1)]
        errors: list = []
        actors = [
            _Interceptor(node, channels[i], channels[i + 1], errors)
            for i, node in enumerate(self.chain)
        ]
        for a in actors:
            a.start()

        feeds = list(feeds)

        def feed():  # the source side runs in its own thread so a full
            for seq, payload in enumerate(feeds):  # pipeline backpressures
                channels[0].put(pickle.dumps((_DATA, seq, payload)))  # here,
            channels[0].put(pickle.dumps((_STOP, len(feeds), None)))  # not in
        feeder = threading.Thread(target=feed, daemon=True)  # the collector
        feeder.start()
        outs = []
        while True:
            raw = channels[-1].get()
            if raw is None:
                break
            kind, seq, payload = pickle.loads(raw)
            if kind == _STOP:
                break
            outs.append((seq, payload))
        # close before joining: a failed stage leaves upstream actors (and the
        # feeder) blocked in put() on full channels — closing unblocks them so
        # the error surfaces immediately instead of after join timeouts
        for ch in channels:
            ch.close()
        feeder.join(timeout=30)
        for a in actors:
            a.join(timeout=30)
        if errors:
            name, exc = errors[0]
            raise RuntimeError(f"interceptor '{name}' failed: {exc!r}") from exc
        outs.sort(key=lambda t: t[0] if isinstance(t[0], tuple) else (t[0], 0))
        return [p for _, p in outs]


class FleetExecutor:
    """User entry (reference fleet_executor.h FleetExecutor::Init/Run): build
    a chain of TaskNodes, then ``run(feeds)`` streams payloads through with
    stage overlap. For model stages pass a jitted callable (e.g. a
    ``paddle.inference`` Predictor's run) as the node fn."""

    def __init__(self, exe_desc: Optional[dict] = None):
        self.exe_desc = exe_desc or {}
        self._carrier: Optional[Carrier] = None

    def init(self, task_nodes: List[TaskNode], capacity: int = 8):
        # validate: linear chain (the reference's common serving topology);
        # amplifiers may expand, sinks must terminate
        for i, n in enumerate(task_nodes[:-1]):
            if n.downstream and task_nodes[i + 1] not in n.downstream:
                raise ValueError(f"task {n.name} downstream edges disagree with the chain order")
        self._carrier = Carrier(task_nodes, capacity)
        return self

    def run(self, feeds) -> list:
        if self._carrier is None:
            raise RuntimeError("FleetExecutor.init(task_nodes) first")
        return self._carrier.run(list(feeds))
