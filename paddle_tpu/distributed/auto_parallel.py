"""Auto-parallel user surface (parity:
python/paddle/distributed/auto_parallel/interface.py shard_tensor/shard_op,
ProcessMesh, and a minimal Engine — auto_parallel/engine.py:50 Engine,
:255 fit).

TPU-first: the reference's Completer/Partitioner/Resharder pipeline (dist-
attr propagation over a serial program) is exactly what XLA's GSPMD
partitioner does from sharding annotations, so the user surface lowers to:

* ``ProcessMesh``        -> ``jax.sharding.Mesh``
* ``shard_tensor``       -> ``dist_spec`` on parameters (consumed by the
                            TrainStep in/out shardings) or an immediate
                            ``with_sharding_constraint`` on activations
* ``shard_op``           -> constraint on the op's outputs
* ``Engine``             -> a sharded ``jit.TrainStep`` over the mesh

Everything after the annotations — propagation, resharding, collective
insertion — is GSPMD's job.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor, unwrap


class ProcessMesh:
    """Parity: auto_parallel ProcessMesh. ``mesh`` is an int array of device
    ordinals (shape = mesh topology); ``dim_names`` name the axes."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.ravel().tolist()
        self.dim_names = list(dim_names) if dim_names else [f"d{i}" for i in range(arr.ndim)]
        devs = jax.devices()
        grid = np.array([devs[i] for i in self.process_ids]).reshape(arr.shape)
        self.jax_mesh = Mesh(grid, tuple(self.dim_names))

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


class ShardingSpecError(ValueError):
    """A shard_tensor/dims_mapping annotation that cannot be placed on the
    given mesh — raised at annotation time with the exact offending entry,
    instead of deferring to a cryptic XLA partitioner failure at compile."""


def _spec_from_dims_mapping(pm: ProcessMesh, dims_mapping: Sequence[int]) -> P:
    """Reference dist-attr encoding: dims_mapping[i] = mesh dim for tensor
    dim i, or -1 for replicated."""
    seen = set()
    for i, m in enumerate(dims_mapping):
        if m == -1:
            continue
        if not isinstance(m, int) or not (0 <= m < pm.ndim):
            raise ShardingSpecError(
                f"dims_mapping[{i}] = {m!r} is not a valid mesh dim for "
                f"{pm!r}: expected -1 (replicated) or 0..{pm.ndim - 1}")
        if m in seen:
            raise ShardingSpecError(
                f"dims_mapping {list(dims_mapping)} maps mesh dim {m} "
                f"({pm.dim_names[m]!r}) to two tensor dims; a mesh axis can "
                "shard at most one dim of a tensor")
        seen.add(m)
    entries = [None if m == -1 else pm.dim_names[m] for m in dims_mapping]
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def _validate_spec(pm: ProcessMesh, entries: Sequence, ndim: int, what: str) -> None:
    """Spec rank must fit the tensor rank and every named axis must exist
    in the mesh (and be used at most once)."""
    if len(entries) > ndim:
        raise ShardingSpecError(
            f"{what}: spec {list(entries)} has {len(entries)} entries but "
            f"the tensor has only {ndim} dims")
    seen = set()
    for i, e in enumerate(entries):
        if e is None:
            continue
        axes = e if isinstance(e, (tuple, list)) else (e,)
        for ax in axes:
            if ax not in pm.dim_names:
                raise ShardingSpecError(
                    f"{what}: spec entry {i} names mesh axis {ax!r}, which "
                    f"does not exist in {pm!r} (axes: {pm.dim_names})")
            if ax in seen:
                raise ShardingSpecError(
                    f"{what}: mesh axis {ax!r} appears on two tensor dims "
                    f"in spec {list(entries)}; an axis shards at most one dim")
            seen.add(ax)


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec: Sequence = None, dist_attr: dict = None):
    """Annotate a tensor's sharding (interface.py shard_tensor).

    Accepts either the 2.x ``dist_attr={"process_mesh": .., "dims_mapping":
    [..]}`` or the newer ``shard_spec=[axis_name|None, ...]``. Parameters
    keep the spec as ``dist_spec`` (picked up by fleet/TrainStep input
    shardings); non-parameter tensors get an immediate sharding constraint
    (under jit) / device_put (eager).
    """
    x = x if isinstance(x, Tensor) else Tensor(x)
    if dist_attr is not None:
        process_mesh = dist_attr.get("process_mesh", process_mesh)
        assert process_mesh is not None, "shard_tensor needs a ProcessMesh"
        mapping = dist_attr["dims_mapping"]
        if len(mapping) != x.ndim:
            raise ShardingSpecError(
                f"shard_tensor: dims_mapping {list(mapping)} has "
                f"{len(mapping)} entries but the tensor has {x.ndim} dims "
                f"(shape {tuple(x.shape)})")
        spec = _spec_from_dims_mapping(process_mesh, mapping)
    else:
        assert process_mesh is not None, "shard_tensor needs a ProcessMesh"
        entries = [s for s in (shard_spec or [])]
        _validate_spec(process_mesh, entries, x.ndim,
                       f"shard_tensor on shape {tuple(x.shape)}")
        while entries and entries[-1] is None:
            entries.pop()
        spec = P(*entries)
    x.dist_spec = spec
    x.process_mesh = process_mesh
    x.is_distributed = True
    sharding = NamedSharding(process_mesh.jax_mesh, spec)
    if getattr(x, "trainable", False) or not x.stop_gradient:
        return x  # parameter: spec consumed at TrainStep build time
    try:
        x._value = jax.lax.with_sharding_constraint(x._value, sharding)
    except (ValueError, TypeError):
        x._value = jax.device_put(x._value, sharding)
    return x


def shard_op(op_fn, process_mesh: ProcessMesh = None, in_shard_specs=None, out_shard_specs=None, dist_attr: dict = None):
    """Wrap a callable so its tensor outputs carry a sharding constraint
    (interface.py shard_op)."""

    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        specs = out_shard_specs or [None] * len(outs)
        for o, s in zip(outs, specs):
            if s is None or not isinstance(o, Tensor):
                continue
            entries = list(s)
            while entries and entries[-1] is None:
                entries.pop()
            sharding = NamedSharding(process_mesh.jax_mesh, P(*entries))
            try:
                o._value = jax.lax.with_sharding_constraint(o._value, sharding)
            except (ValueError, TypeError):
                pass
        return out

    return wrapped


class Engine:
    """Minimal auto-parallel Engine (engine.py:50): prepare() builds one
    sharded TrainStep from the model's shard_tensor annotations; fit()
    drives it."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None, strategy=None, process_mesh: ProcessMesh = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy
        self.process_mesh = process_mesh
        self._step = None
        self.shard_report = None  # SpmdReport from the prepare() pre-flight

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train",
                analyze=None):
        """Build the sharded TrainStep; with ``inputs_spec`` (and
        ``FLAGS_shard_check`` or ``analyze=True``), also pre-flight the
        lowered program through the SPMD analyzer (paddle_tpu.analysis.spmd
        PTA2xx) BEFORE any batch is dispatched — the verdict lands on
        ``self.shard_report`` (reshard bytes, collective schedule,
        per-device memory), budget overruns raise here."""
        from ..distributed.sharding import place_state, state_shardings
        from ..framework.flags import flag as _flag
        from ..jit import TrainStep

        mesh = self.process_mesh.jax_mesh if self.process_mesh else None
        mp_specs = {n: p.dist_spec for n, p in self.model.named_parameters() if getattr(p, "dist_spec", None) is not None}
        step = TrainStep(self.model, self.optimizer, self.loss)
        if mesh is not None:
            shardings = state_shardings(step.state, mesh, stage=0, mp_specs=mp_specs)
            step.state = place_state(step.state, shardings)
            step._jit = jax.jit(step._step, donate_argnums=0, in_shardings=(shardings, None), out_shardings=(shardings, None))
            step.mesh = mesh
            step.state_shardings = shardings
            step._state_shardings = shardings
        self._step = step
        if analyze is None:
            analyze = bool(_flag("FLAGS_shard_check"))
        if analyze and inputs_spec is not None:
            self.shard_report = self._preflight(inputs_spec, labels_spec)
        return self

    def _preflight(self, inputs_spec, labels_spec):
        """Lower the step on abstract batch shapes (nothing runs) and hand
        the executable to the analyzer — the planner-evaluator path: a
        candidate mesh/spec assignment gets its machine-readable verdict
        from shapes alone."""
        from ..analysis import spmd as _spmd
        from .planner import abstract_inputs

        mesh = self.process_mesh.jax_mesh if self.process_mesh else None
        # dynamic (None/-1) dims need a concrete probe extent; the mesh size
        # divides every axis product by construction
        fill = int(mesh.size) if mesh is not None else 1
        batch = (abstract_inputs(inputs_spec, fill),
                 abstract_inputs(labels_spec if labels_spec is not None
                                 else inputs_spec, fill))
        step = self._step
        from ..observability.introspect import aot_compile

        compiled, _ = aot_compile(step._jit, (step.state, batch))
        if compiled is None:
            return None
        shardings = step._state_shardings
        psh = shardings.get("params") if isinstance(shardings, dict) else None
        return _spmd.shard_check(
            compiled, component="auto_parallel", label="engine.prepare",
            kind="train", params=step.state.get("params"),
            param_shardings=psh)

    def plan(self, n_devices=None, inputs_spec=None, labels_spec=None, **kw):
        """Rank parallel plans for this engine's model (the auto-search the
        reference Engine runs under ``strategy.auto_mode``): delegates to
        :func:`paddle_tpu.distributed.planner.search` with the engine's
        model/loss/optimizer. ``n_devices`` defaults to the engine's mesh
        size (or every visible device)."""
        from . import planner as _planner

        if n_devices is None:
            n_devices = (int(self.process_mesh.jax_mesh.size)
                         if self.process_mesh else len(jax.devices()))
        return _planner.search(self.model, n_devices, inputs_spec=inputs_spec,
                               labels_spec=labels_spec, loss=self.loss,
                               optimizer=self.optimizer, **kw)

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None, log_freq=10, verbose=0):
        if self._step is None:
            self.prepare()
        history = []
        for _ in range(epochs):
            losses = []
            for i, batch in enumerate(train_data):
                if steps_per_epoch and i >= steps_per_epoch:
                    break
                x, y = (batch[0], batch[1]) if isinstance(batch, (list, tuple)) and len(batch) >= 2 else (batch, batch)
                m = self._step(x, y)
                losses.append(float(m["loss"]))
            history.append(float(np.mean(losses)) if losses else 0.0)
        return history

    @property
    def main_program(self):  # static-graph accessor kept for API shape
        return None
