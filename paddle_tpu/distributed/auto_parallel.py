"""Auto-parallel user surface (parity:
python/paddle/distributed/auto_parallel/interface.py shard_tensor/shard_op,
ProcessMesh, and a minimal Engine — auto_parallel/engine.py:50 Engine,
:255 fit).

TPU-first: the reference's Completer/Partitioner/Resharder pipeline (dist-
attr propagation over a serial program) is exactly what XLA's GSPMD
partitioner does from sharding annotations, so the user surface lowers to:

* ``ProcessMesh``        -> ``jax.sharding.Mesh``
* ``shard_tensor``       -> ``dist_spec`` on parameters (consumed by the
                            TrainStep in/out shardings) or an immediate
                            ``with_sharding_constraint`` on activations
* ``shard_op``           -> constraint on the op's outputs
* ``Engine``             -> a sharded ``jit.TrainStep`` over the mesh

Everything after the annotations — propagation, resharding, collective
insertion — is GSPMD's job.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor, unwrap


class ProcessMesh:
    """Parity: auto_parallel ProcessMesh. ``mesh`` is an int array of device
    ordinals (shape = mesh topology); ``dim_names`` name the axes."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None):
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.process_ids = arr.ravel().tolist()
        self.dim_names = list(dim_names) if dim_names else [f"d{i}" for i in range(arr.ndim)]
        devs = jax.devices()
        grid = np.array([devs[i] for i in self.process_ids]).reshape(arr.shape)
        self.jax_mesh = Mesh(grid, tuple(self.dim_names))

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _spec_from_dims_mapping(pm: ProcessMesh, dims_mapping: Sequence[int]) -> P:
    """Reference dist-attr encoding: dims_mapping[i] = mesh dim for tensor
    dim i, or -1 for replicated."""
    entries = [None if m == -1 else pm.dim_names[m] for m in dims_mapping]
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec: Sequence = None, dist_attr: dict = None):
    """Annotate a tensor's sharding (interface.py shard_tensor).

    Accepts either the 2.x ``dist_attr={"process_mesh": .., "dims_mapping":
    [..]}`` or the newer ``shard_spec=[axis_name|None, ...]``. Parameters
    keep the spec as ``dist_spec`` (picked up by fleet/TrainStep input
    shardings); non-parameter tensors get an immediate sharding constraint
    (under jit) / device_put (eager).
    """
    if dist_attr is not None:
        process_mesh = dist_attr.get("process_mesh", process_mesh)
        spec = _spec_from_dims_mapping(process_mesh, dist_attr["dims_mapping"])
    else:
        entries = [s for s in (shard_spec or [])]
        while entries and entries[-1] is None:
            entries.pop()
        spec = P(*entries)
    assert process_mesh is not None, "shard_tensor needs a ProcessMesh"
    x = x if isinstance(x, Tensor) else Tensor(x)
    x.dist_spec = spec
    x.process_mesh = process_mesh
    x.is_distributed = True
    sharding = NamedSharding(process_mesh.jax_mesh, spec)
    if getattr(x, "trainable", False) or not x.stop_gradient:
        return x  # parameter: spec consumed at TrainStep build time
    try:
        x._value = jax.lax.with_sharding_constraint(x._value, sharding)
    except (ValueError, TypeError):
        x._value = jax.device_put(x._value, sharding)
    return x


def shard_op(op_fn, process_mesh: ProcessMesh = None, in_shard_specs=None, out_shard_specs=None, dist_attr: dict = None):
    """Wrap a callable so its tensor outputs carry a sharding constraint
    (interface.py shard_op)."""

    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        specs = out_shard_specs or [None] * len(outs)
        for o, s in zip(outs, specs):
            if s is None or not isinstance(o, Tensor):
                continue
            entries = list(s)
            while entries and entries[-1] is None:
                entries.pop()
            sharding = NamedSharding(process_mesh.jax_mesh, P(*entries))
            try:
                o._value = jax.lax.with_sharding_constraint(o._value, sharding)
            except (ValueError, TypeError):
                pass
        return out

    return wrapped


class Engine:
    """Minimal auto-parallel Engine (engine.py:50): prepare() builds one
    sharded TrainStep from the model's shard_tensor annotations; fit()
    drives it."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None, strategy=None, process_mesh: ProcessMesh = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy
        self.process_mesh = process_mesh
        self._step = None

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        from ..distributed.sharding import state_shardings
        from ..jit import TrainStep

        mesh = self.process_mesh.jax_mesh if self.process_mesh else None
        mp_specs = {n: p.dist_spec for n, p in self.model.named_parameters() if getattr(p, "dist_spec", None) is not None}
        step = TrainStep(self.model, self.optimizer, self.loss)
        if mesh is not None:
            shardings = state_shardings(step.state, mesh, stage=0, mp_specs=mp_specs)
            step.state = jax.device_put(step.state, shardings)
            step._jit = jax.jit(step._step, donate_argnums=0, in_shardings=(shardings, None), out_shardings=(shardings, None))
            step.mesh = mesh
            step.state_shardings = shardings
        self._step = step
        return self

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None, log_freq=10, verbose=0):
        if self._step is None:
            self.prepare()
        history = []
        for _ in range(epochs):
            losses = []
            for i, batch in enumerate(train_data):
                if steps_per_epoch and i >= steps_per_epoch:
                    break
                x, y = (batch[0], batch[1]) if isinstance(batch, (list, tuple)) and len(batch) >= 2 else (batch, batch)
                m = self._step(x, y)
                losses.append(float(m["loss"]))
            history.append(float(np.mean(losses)) if losses else 0.0)
        return history

    @property
    def main_program(self):  # static-graph accessor kept for API shape
        return None
