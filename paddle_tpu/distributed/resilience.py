"""Fault-tolerant training runtime: checkpoints that survive torn writes,
store ops that survive transient failures, and a supervisor that survives
membership churn.

Parity: fleet/elastic/manager.py's HOLD -> RESTART protocol plus the
fault-tolerance the reference delegates to infra (etcd leases, k8s
restarts), rebuilt on this repo's own primitives:

- **CheckpointManager** wraps ``checkpoint.save_state/load_state`` with
  write-to-temp-then-rename publication, a manifest carrying the step and
  per-array CRCs (``checkpoint.checksum_pytree``), keep-last-k rotation,
  and ``restore_latest`` that walks back past corrupt/truncated
  checkpoints to the newest one whose checksums verify.
- **retry** decorates transient store/IO calls with bounded
  exponential-backoff retries. ``FLAGS_store_retry_jitter`` (default on)
  applies capped FULL jitter — uniform(0, cap) — seeded through
  ``framework.random.host_generator``, so N replicas retrying one dead
  store de-correlate while injected-fault tests still replay exactly.
- **watchdog** arms a timer around an uncancellable block (an XLA
  collective, a blocking store op) and reports — to stderr and an optional
  handler — when it is still pending past the deadline, instead of the
  silent infinite hang a dead peer otherwise produces.
- **run_resilient** is the elastic supervisor: it consumes
  ``ElasticNode.alive_nodes()`` membership changes and worker-raised
  faults, and executes HOLD -> checkpoint -> wait-for-settle -> resume
  with rescaled ranks, bounded restart attempts, and backoff.

Every recovery path here is proven under injected faults (testing/chaos.py)
by tests/test_resilience.py — on CPU, no real cluster required.
"""
from __future__ import annotations

import functools
import json
import os
import re
import shutil
import sys
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from ..framework.flags import flag
from ..observability import runlog as _runlog
from ..observability import span as _span
from ..observability.metrics import counter_inc as _counter_inc
from ..testing import chaos
from . import checkpoint as ckpt_mod
from .store import BarrierTimeoutError  # noqa: F401  (re-export: one seam)

_MANIFEST = "manifest.json"
_STEP_RE = re.compile(r"^step_(\d{8})$")


class CheckpointCorruption(RuntimeError):
    """A checkpoint on disk failed verification (missing manifest, unreadable
    arrays, or checksum mismatch)."""


class WorkerFault(RuntimeError):
    """Raised by a train step to signal a recoverable worker fault the
    supervisor should answer with checkpoint + restart (e.g. a failed
    collective, a preemption notice)."""


# --------------------------------------------------------------------------
# CheckpointManager
# --------------------------------------------------------------------------


class CheckpointManager:
    """Rotating, integrity-checked checkpoints under one directory.

    Layout: ``<dir>/step_00000042/{state, manifest.json}``. A checkpoint is
    *published* by renaming its temp directory into place, and *valid* only
    if the manifest — written last, after the arrays are durable — is
    present and every per-array CRC matches. A crash at any point therefore
    leaves either the previous checkpoints untouched (temp dir never
    renamed, GC'd later) or a complete new one; there is no window where
    the latest checkpoint is half-written yet looks restorable.
    """

    def __init__(self, directory: str, keep_last_k: int = 3):
        if keep_last_k < 1:
            raise ValueError(f"keep_last_k must be >= 1, got {keep_last_k}")
        self.directory = os.path.abspath(directory)
        self.keep_last_k = keep_last_k
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------- layout
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def steps(self) -> List[int]:
        """Published step numbers, ascending (validity not yet checked)."""
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    # --------------------------------------------------------------- save
    def save(self, state: Any, step: int) -> str:
        """Atomically publish ``state`` as the checkpoint for ``step``."""
        with _span("checkpoint.save") as sp:
            final = self._save(state, step)
        _counter_inc("checkpoint.saves")
        _runlog.emit("checkpoint_save", step=step, path=final,
                     seconds=sp.seconds)
        return final

    def _save(self, state: Any, step: int) -> str:
        final = self._step_dir(step)
        tmp = os.path.join(self.directory, f".tmp-step_{step:08d}-{os.getpid()}")
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        ckpt_mod.save_state(state, os.path.join(tmp, "state"))
        # kill-mid-save lands here: arrays on disk, manifest absent -> the
        # temp dir is never published and restore skips it entirely
        chaos.crash_if_due("checkpoint_save", step)
        manifest = {"format": 1, "step": step,
                    "leaves": ckpt_mod.checksum_pytree(state)}
        mpath = os.path.join(tmp, _MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)  # same-step re-save: replace
        os.rename(tmp, final)
        if chaos.corrupt_due():
            _corrupt_array_data(final)
            _runlog.emit("chaos_inject", step=step, kind="corrupt_ckpt",
                         path=final)
        self.gc()
        return final

    # ------------------------------------------------------------ restore
    def restore_latest(self, target: Optional[Any] = None,
                       shardings: Optional[Any] = None,
                       ) -> Optional[Tuple[Any, int]]:
        """(state, step) from the newest checkpoint that passes
        verification, walking backwards past corrupt/truncated ones;
        None when no valid checkpoint exists.

        ``target``/``shardings`` need NOT match the sharding the checkpoint
        was saved under: after CRC verification the state is routed through
        :mod:`~paddle_tpu.distributed.converter` — host gather, then one
        ``device_put`` per leaf under the new ``NamedSharding`` — so a
        checkpoint written on mesh A restores onto mesh B (elastic
        scale-up/down, the reference converter.py capability). A target the
        checkpoint *cannot* convert to (shape/dtype/structure drift) raises
        :class:`~.converter.CheckpointConversionError` naming the first
        mismatched leaf — that is a caller bug, not corruption, so it
        propagates instead of falling back to an older checkpoint."""
        from .converter import CheckpointConversionError

        with _span("checkpoint.restore") as sp:
            result = None
            for step in reversed(self.steps()):
                try:
                    result = self._load_verified(step, target, shardings), step
                    break
                except CheckpointConversionError:
                    raise
                except Exception as exc:
                    print(f"[resilience] checkpoint step {step} invalid "
                          f"({type(exc).__name__}: {exc}); falling back",
                          file=sys.stderr)
        if result is not None:
            _counter_inc("checkpoint.restores")
            _runlog.emit("checkpoint_restore", step=result[1],
                         seconds=sp.seconds)
        return result

    def _load_verified(self, step: int, target, shardings) -> Any:
        d = self._step_dir(step)
        mpath = os.path.join(d, _MANIFEST)
        if not os.path.exists(mpath):
            raise CheckpointCorruption(f"{d}: no manifest (interrupted save)")
        with open(mpath) as f:
            manifest = json.load(f)
        # raw restore first: the CRC is computed over the same bytes the
        # manifest recorded at save time (placement-independent), THEN the
        # verified state converts onto the requested target/shardings
        import warnings as _warnings

        with _warnings.catch_warnings():
            # orbax warns that a raw restore re-reads the saved sharding
            # file; the converter re-places every leaf right after, so the
            # saved placement is irrelevant here
            _warnings.filterwarnings("ignore", message=".*sharding info.*")
            state = ckpt_mod.load_state(os.path.join(d, "state"))
        got = ckpt_mod.checksum_pytree(state)
        want = manifest["leaves"]
        bad = sorted(k for k in set(want) | set(got)
                     if want.get(k, {}).get("crc32") != got.get(k, {}).get("crc32"))
        if bad:
            raise CheckpointCorruption(
                f"{d}: checksum mismatch for {bad} (on-disk corruption)")
        if target is not None or shardings is not None:
            from . import converter as _converter

            state = _converter.convert(state, target=target,
                                       shardings=shardings,
                                       label=f"step_{step:08d}")
        return state

    # ----------------------------------------------------------- rotation
    def gc(self):
        """Keep the newest ``keep_last_k`` published checkpoints; drop the
        rest plus any stale temp dirs from crashed saves."""
        for step in self.steps()[:-self.keep_last_k]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-step_"):
                p = os.path.join(self.directory, name)
                # a LIVE writer's temp dir belongs to this pid; stale ones
                # come from crashed saves and are safe to reap
                if not name.endswith(f"-{os.getpid()}"):
                    shutil.rmtree(p, ignore_errors=True)


def _corrupt_array_data(step_dir: str):
    """Chaos helper: bit-flip every array-data chunk (orbax/ocdbt keeps
    them under ``d/``) of a published checkpoint. The manifest stays
    intact, so the checkpoint still LOOKS restorable — only loading it
    (loader-level error) or verifying it (checksum mismatch) can tell."""
    for root, _, files in os.walk(step_dir):
        if os.path.basename(root) != "d":
            continue
        for f in files:
            p = os.path.join(root, f)
            with open(p, "r+b") as fh:
                data = fh.read()
                fh.seek(0)
                fh.write(bytes(b ^ 0xFF for b in data))


# --------------------------------------------------------------------------
# Store hardening
# --------------------------------------------------------------------------


def retry(max_attempts: int = 3, base_delay: float = 0.05,
          max_delay: float = 2.0,
          retry_on: Tuple[type, ...] = (OSError, TimeoutError),
          jitter: Optional[bool] = None,
          deadline_s: Optional[float] = None):
    """Bounded exponential-backoff retry for transient store/IO failures.

    Attempt i's backoff cap is ``min(max_delay, base_delay * 2**i)``; after
    ``max_attempts`` failures the last exception propagates unchanged.

    ``deadline_s`` adds an overall wall-clock budget per CALL (measured
    from its first attempt): once the budget is spent no further attempt
    is made and the last exception propagates unchanged — the bound a
    caller's SLA actually needs, where ``max_attempts x max_delay`` only
    bounds the sleep time and says nothing about how long the attempts
    themselves block. A backoff sleep is clamped to the remaining budget,
    so the final retry fires just before the deadline instead of
    overshooting it. None (the default) keeps the attempts-only bound.

    ``jitter`` selects the sleep inside that cap (None defers to
    ``FLAGS_store_retry_jitter``, read per call so ``set_flags`` applies to
    already-decorated functions):

    - **full jitter** (the AWS discipline): sleep ``uniform(0, cap)``. N
      replicas hammering a dead store spread their retries across the whole
      window instead of thundering-herding on the same schedule. The stream
      comes from :func:`framework.random.host_generator` seeded on
      (``paddle.seed``, the decorated function's name, PADDLE_TRAINER_ID) —
      bitwise-replayable under chaos tests, de-correlated across ranks.
    - **off**: the pre-jitter deterministic sleeps (exactly ``cap``).
    """
    if max_attempts < 1:
        raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
    if deadline_s is not None and deadline_s <= 0:
        raise ValueError(f"deadline_s must be > 0, got {deadline_s}")

    def deco(fn: Callable):
        from ..framework import random as _random

        tag = (f"retry/{getattr(fn, '__qualname__', fn)}"
               f"/{os.environ.get('PADDLE_TRAINER_ID', '0')}")
        rng_box: list = []  # created lazily so paddle.seed set later applies

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.monotonic() if deadline_s is not None else 0.0
            for attempt in range(max_attempts):
                try:
                    return fn(*args, **kwargs)
                except retry_on:
                    if attempt == max_attempts - 1:
                        raise
                    cap = min(max_delay, base_delay * (2 ** attempt))
                    use = flag("FLAGS_store_retry_jitter") if jitter is None else jitter
                    if use:
                        if not rng_box:
                            rng_box.append(_random.host_generator(tag))  # noqa: PTA104 (host-side retry backoff, never traced)
                        cap = float(rng_box[0].uniform(0.0, cap))
                    if deadline_s is not None:
                        remaining = deadline_s - (time.monotonic() - t0)
                        if remaining <= 0:
                            raise  # budget spent: last exception unchanged
                        cap = min(cap, remaining)
                    time.sleep(cap)

        return wrapper

    return deco


class RetryingStore:
    """Proxy wrapping a TCPStore's transient-failure-prone ops (set/get/
    add/wait/delete_key/num_keys) in the ``retry`` decorator; everything
    else passes through. ``jitter`` has :func:`retry` semantics (None
    defers to ``FLAGS_store_retry_jitter`` — full jitter by default, so a
    fleet of replicas retrying one dead store doesn't thundering-herd);
    ``deadline_s`` is the per-call wall-clock retry budget (None keeps the
    attempts-only bound)."""

    _RETRIED = ("set", "get", "add", "wait", "delete_key", "num_keys")

    def __init__(self, store, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, jitter: Optional[bool] = None,
                 deadline_s: Optional[float] = None):
        self._store = store
        deco = retry(max_attempts=max_attempts, base_delay=base_delay,
                     max_delay=max_delay, retry_on=(OSError,), jitter=jitter,
                     deadline_s=deadline_s)
        for name in self._RETRIED:
            setattr(self, name, deco(getattr(store, name)))

    def __getattr__(self, name):
        return getattr(self._store, name)


def watchdog(name: str, timeout: Optional[float] = None,
             on_timeout: Optional[Callable[[str, float], None]] = None):
    """Context manager arming a timer around an uncancellable block (XLA
    collective, blocking store op). If the block is still pending after
    ``timeout`` seconds, the handler runs on a daemon thread — default:
    print a diagnostic to stderr — turning a silent distributed hang into
    an attributable report. ``timeout`` defaults to
    FLAGS_collective_timeout_s; <= 0 disarms (zero overhead).

    The block itself keeps running (XLA gives no cancellation handle);
    pair with the elastic layer, whose membership view replaces the hung
    worker.
    """
    import contextlib

    @contextlib.contextmanager
    def cm():
        tmo = flag("FLAGS_collective_timeout_s") if timeout is None else timeout
        if not tmo or tmo <= 0:
            yield
            return
        t0 = time.monotonic()

        def fire():
            elapsed = time.monotonic() - t0
            _runlog.emit("collective_timeout", name=name, seconds=elapsed,
                         deadline=tmo)
            if on_timeout is not None:
                on_timeout(name, elapsed)
            else:
                print(f"[resilience][watchdog] {name!r} still pending after "
                      f"{elapsed:.1f}s (deadline {tmo:g}s) — a peer is likely "
                      "dead or partitioned", file=sys.stderr)

        timer = threading.Timer(tmo, fire)
        timer.daemon = True
        timer.start()
        try:
            yield
        finally:
            timer.cancel()

    return cm()


# --------------------------------------------------------------------------
# Elastic supervisor
# --------------------------------------------------------------------------


class _MembershipChanged(Exception):
    """Internal: the alive set no longer matches the working membership."""


def run_resilient(train_step_fn: Callable[[Any, int, List[int]], Any], *,
                  node, manager: CheckpointManager, init_state: Any,
                  num_steps: int, min_nodes: int = 1,
                  max_nodes: Optional[int] = None, checkpoint_every: int = 1,
                  max_restarts: int = 3, backoff: float = 0.2,
                  settle: float = 0.5, deadline: float = 60.0,
                  membership_check_every: int = 1,
                  on_event: Optional[Callable[[str, dict], None]] = None,
                  shardings: Optional[Any] = None,
                  on_rescale: Optional[Callable[[List[int], Any], Any]] = None,
                  ) -> Tuple[Any, int]:
    """Supervised elastic training loop: detect, checkpoint, rescale, resume.

    ``train_step_fn(state, step, members) -> state`` runs one step;
    ``members`` is the settled alive set (ascending node ids — a node's
    index is its rescaled rank, reference manager semantics). Recovery
    protocol on a membership change or a worker-raised ``WorkerFault``/
    injected crash:

      HOLD      stop stepping; checkpoint in-progress state at once
      SETTLE    ``node.wait_for(min_nodes, max_nodes, settle)`` until the
                alive set is stable inside the allowed range
      RESCALE   when the settled membership differs and ``on_rescale`` is
                given: ``on_rescale(members, state)`` re-plans for the new
                topology (e.g. ``planner.elastic_replan`` — searches the
                plan cache, builds the new sharded TrainStep and compiles
                it NOW, inside the HOLD window) and returns the new
                ``(restore_target, restore_shardings)`` pair
      RESUME    restore the newest valid checkpoint — resharded through
                the converter onto the (possibly new) target/shardings —
                and continue from its step with the rescaled membership

    ``shardings`` places the initial restore (same semantics as
    :meth:`CheckpointManager.restore_latest`). A topology change without
    ``on_rescale`` keeps the old target — same-topology behavior is
    unchanged.

    A :class:`paddle_tpu.stability.DivergenceFault` (raised by a
    ``HealthMonitor`` inside ``train_step_fn``) follows the same protocol
    EXCEPT the HOLD save: numerically poisoned state is never persisted —
    the restore rewinds past the divergence instead.

    Restart attempts are bounded by ``max_restarts`` with exponential
    backoff; the fault that exhausts the budget propagates. Returns
    ``(final_state, restarts_used)``.
    """
    from ..observability import exporter as _exporter
    from ..observability import flightrec as _flightrec
    from ..observability import trace as _trace

    members = node.wait_for(min_nodes, max_nodes, settle=settle,
                            deadline=deadline)
    state, step = init_state, 0
    restore_target, restore_shardings = init_state, shardings
    restored = manager.restore_latest(target=restore_target,
                                      shardings=restore_shardings)
    if restored is not None:
        state, step = restored
    restarts = 0
    # one trace id for the whole supervised run: every step event and every
    # incident span (hold / rollback / rescale / resume) correlates to it
    run_trace = _trace.new_trace_id("resilient")
    # live export for the long-lived worker (no-op at FLAGS_metrics_port=0)
    _exporter.ensure_started(store=getattr(node, "store", None),
                             rank=getattr(node, "node_id", 0))

    def _emit(kind, **info):
        if on_event is not None:
            on_event(kind, info)

    def _membership_events(old, new, step_):
        for node_id in sorted(set(new) - set(old)):
            _runlog.emit("worker_join", step=step_, node=node_id,
                         members=list(new))
        for node_id in sorted(set(old) - set(new)):
            _runlog.emit("worker_leave", step=step_, node=node_id,
                         members=list(new))

    _membership_events([], members, step)
    _emit("start", step=step, members=members)
    with _trace.attach(run_trace):  # step events inherit the run's trace id
        while step < num_steps:
            try:
                if membership_check_every and step % membership_check_every == 0:
                    alive = node.alive_nodes()
                    if alive != members:
                        raise _MembershipChanged(f"{members} -> {alive}")
                chaos.crash_if_due("train_step", step)
                state = train_step_fn(state, step, members)
            except (WorkerFault, chaos.ChaosCrash, _MembershipChanged) as fault:
                if restarts >= max_restarts:
                    _emit("giveup", step=step, fault=repr(fault))
                    raise
                restarts += 1
                _emit("hold", step=step, fault=repr(fault), restart=restarts)
                _trace.span_event("resilient.hold", trace_id=run_trace,
                                  step=step, restart=restarts,
                                  fault=type(fault).__name__)
                from ..stability import DivergenceFault

                if isinstance(fault, DivergenceFault):
                    # divergence rewind: the in-flight state is numerically
                    # poisoned — restore WITHOUT persisting it first
                    _counter_inc("stability.rollbacks")
                    _runlog.emit("rollback", step=step, reason=str(fault),
                                 rollbacks=restarts, trace=run_trace)
                    _flightrec.dump("divergence", fault, step=step,
                                    restart=restarts)
                else:
                    manager.save(state, step)  # HOLD: make current progress durable
                time.sleep(backoff * (2 ** (restarts - 1)))
                prev_members = members
                members = node.wait_for(min_nodes, max_nodes, settle=settle,
                                        deadline=deadline)
                _membership_events(prev_members, members, step)
                if on_rescale is not None and members != prev_members:
                    # elastic re-plan during the HOLD window: the hook
                    # searches/builds for the new topology (compiling the new
                    # mesh's program now, while nothing else runs) and hands
                    # back the target+shardings the checkpoint reshards onto
                    with _trace.trace_span("resilient.rescale",
                                           trace_id=run_trace, step=step,
                                           members=list(members)):
                        rescaled = on_rescale(members, state)
                    if rescaled is not None:
                        if isinstance(rescaled, tuple):
                            restore_target, restore_shardings = rescaled
                        else:
                            restore_target = rescaled
                        state = restore_target
                restored = manager.restore_latest(target=restore_target,
                                                  shardings=restore_shardings)
                if restored is not None:
                    state, step = restored
                _emit("resume", step=step, members=members, restart=restarts)
                _trace.span_event("resilient.resume", trace_id=run_trace,
                                  step=step, restart=restarts,
                                  members=list(members))
                continue  # noqa: PTA103 (host-side, never traced)
            step += 1
            if checkpoint_every and step % checkpoint_every == 0:
                manager.save(state, step)
    manager.save(state, num_steps)
    _emit("done", step=num_steps, restarts=restarts)
    return state, restarts
