"""paddle_tpu.distributed (parity: python/paddle/distributed)."""
from . import checkpoint  # noqa: F401
from . import fleet as fleet_mod  # noqa: F401
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    alltoall,
    barrier,
    broadcast,
    get_group,
    new_group,
    ppermute,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env  # noqa: F401
from .fleet_executor import Carrier, FleetExecutor, TaskNode  # noqa: F401
from . import utils  # noqa: F401
from .fleet import Fleet, fleet  # noqa: F401
from .moe import MoELayer  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    TensorParallel,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from .auto_parallel import (  # noqa: F401
    Engine,
    ProcessMesh,
    ShardingSpecError,
    shard_op,
    shard_tensor,
)
from .parallel import DataParallel, spawn  # noqa: F401
from .pipeline import LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc, spmd_pipeline  # noqa: F401
from .recompute import recompute, remat  # noqa: F401
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
from .sharding import build_state_specs, group_sharded_parallel, state_shardings  # noqa: F401
from .strategy import DistributedStrategy  # noqa: F401
from .topology import AXES, HybridCommunicateGroup, build_mesh  # noqa: F401
from .store import BarrierTimeoutError, TCPStore, rendezvous_store  # noqa: F401
from .resilience import (  # noqa: F401
    CheckpointCorruption,
    CheckpointManager,
    RetryingStore,
    WorkerFault,
    retry,
    run_resilient,
    watchdog,
)
from . import converter  # noqa: F401
from . import planner  # noqa: F401
from .embedding import (  # noqa: F401
    EmbeddingCheckpointRotation,
    ShardedEmbedding,
    sharded_embedding_lookup,
)
from .converter import CheckpointConversionError  # noqa: F401
from .planner import Plan, PlannerError  # noqa: F401
