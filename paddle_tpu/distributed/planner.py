"""Cost-model-driven auto-parallel planner.

Closes the loop ROADMAP has named since PR 9: the SPMD sharding analyzer
(``analysis/spmd.py`` PTA2xx) returns a machine-readable verdict — reshard
bytes, collective schedule, per-device memory — for *any* candidate
mesh/spec assignment from shapes alone. This module is the search on top:

1. **Enumerate** candidate plans: every factorization of the device count
   over the ``dp``/``sdp``/``mp``/``pp`` axes (the MULTICHIP dryrun
   families), crossed with PartitionSpec templates for the parameters
   (the model's own ``dist_spec`` annotations, fully replicated, or any
   user-supplied template) and the ZeRO stage over ``sdp``.
2. **Evaluate** each via the ``Engine.prepare(analyze=True)`` path: the
   step program is lowered on ``ShapeDtypeStruct``s under the candidate
   shardings — nothing is dispatched, no batch exists — and scored from
   the ``SpmdReport`` (ring-accounting reshard/collective bytes), the
   compiled-program cost analysis (flops, bytes accessed) and the
   per-device memory estimate vs ``FLAGS_hbm_budget_mb``. Plans whose
   static state-memory floor already exceeds the budget are pruned
   *before* compiling (the PTA204 rule applied pre-flight); plans whose
   compiled estimate overruns are marked infeasible by the analyzer's
   PTA204 error.
3. **Rank** by predicted step time (``cost_model.predict_step_time``
   roofline: max(compute, HBM) + collectives) — a mis-sharded spec's
   extra all-gathers surface as comm seconds, so it scores strictly worse
   than the clean twin.

Ranked plans are cached as JSON under ``FLAGS_compile_cache_dir/planner/``
keyed on (model fingerprint, device count, input shapes, search space):
a restart pays zero search. Because evaluation compiles the *same* lowered
program the real ``TrainStep`` will dispatch (and stores it in the AOT
executable cache under ``cache_scope="train_step"``), searching during an
elastic HOLD window warm-starts the new mesh's compilation: the resumed
step loads the executable instead of compiling.

Entry points::

    plans = planner.search(model, n_devices, inputs_spec=..., loss=...)
    step  = planner.build_step(model, opt, loss, plans[0])   # sharded TrainStep
    on_rescale = planner.elastic_replan(model, opt, loss, ...)  # run_resilient hook
    python -m paddle_tpu.distributed.planner --devices 8 --json
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Plan", "PlannerError", "mesh_shapes", "annotated_specs",
    "row_sharded_specs", "default_templates",
    "abstract_inputs", "search", "build_step", "elastic_replan",
    "format_plan_table", "main",
]

#: search axes in the canonical (topology.AXES) order; 'sep' is a
#: green-field sequence axis and 'pp' cannot SPMD-compile on the CPU
#: backend (pre-existing PartitionId limitation), so the default space is
#: dp × sdp × mp — pass axes=... to widen.
DEFAULT_AXES: Tuple[str, ...] = ("dp", "sdp", "mp")


class PlannerError(RuntimeError):
    """The search cannot run (no devices, missing specs, ...)."""


# ---------------------------------------------------------------- candidates
def mesh_shapes(n_devices: int, axes: Sequence[str] = DEFAULT_AXES) -> List[Dict[str, int]]:
    """Every ordered factorization of ``n_devices`` over ``axes`` as an
    axis-degree dict (degree-1 axes omitted). ``n_devices=4, axes=(dp,mp)``
    -> ``[{}:dp4, {dp:2,mp:2}, {mp:4}]``-style candidates."""
    axes = tuple(axes)

    def rec(remaining: int, rest: Tuple[str, ...]):
        if not rest:
            if remaining == 1:
                yield {}
            return
        ax = rest[0]
        d = 1
        while d <= remaining:
            if remaining % d == 0:
                for tail in rec(remaining // d, rest[1:]):
                    out = dict(tail)
                    if d > 1:
                        out[ax] = d  # noqa: PTA104 (host-side, never traced)
                    yield out
            d += 1

    seen, out = set(), []
    for m in rec(int(n_devices), axes):
        key = tuple(sorted(m.items()))
        if key not in seen:
            seen.add(key)  # noqa: PTA104 (host-side, never traced)
            out.append(m)  # noqa: PTA104 (host-side, never traced)
    return out


def annotated_specs(model) -> Dict[str, Any]:
    """The model's own ``dist_spec`` annotations (mp_layers /
    ``shard_tensor``) as a param-name -> PartitionSpec template."""
    return {n: p.dist_spec for n, p in model.named_parameters()
            if getattr(p, "dist_spec", None) is not None}


def row_sharded_specs(model) -> Dict[str, Any]:
    """Row-shard specs for params flagged ``_row_shard_axis``
    (``ShardedEmbedding`` tables): a production-vocab table replicated
    across the mesh is exactly the PTA206 waste finding, so the planner's
    default templates must never emit it."""
    from jax.sharding import PartitionSpec as P

    return {n: P(p._row_shard_axis) for n, p in model.named_parameters()
            if getattr(p, "_row_shard_axis", None)}


def default_templates(model) -> Dict[str, Dict[str, Any]]:
    """The template set ``search`` uses when none is supplied: the model's
    annotations (plus embedding row specs) and a replicated baseline —
    which still row-shards ``ShardedEmbedding`` tables, since replicating
    them is never a candidate worth scoring at production vocab sizes."""
    ann = annotated_specs(model)
    row = row_sharded_specs(model)
    templates: Dict[str, Dict[str, Any]] = (
        {"annotated": {**row, **ann}} if (ann or row) else {})
    templates.setdefault("replicated", dict(row))  # noqa: PTA104 (host-side, never traced)
    return templates


def _spec_entries(spec) -> List:
    """PartitionSpec -> JSON-able entry list (None | axis | [axes])."""
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)  # noqa: PTA104 (host-side, never traced)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))  # noqa: PTA104 (host-side, never traced)
        else:
            out.append(str(e))  # noqa: PTA104 (host-side, never traced)
    return out


def _entries_spec(entries: Sequence):
    from jax.sharding import PartitionSpec as P

    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def abstract_inputs(specs, fill: int = 1) -> Tuple:
    """Input specs (static.InputSpec / ShapeDtypeStruct / arrays) ->
    ``jax.ShapeDtypeStruct`` tuple; dynamic dims (None / -1) are filled
    with ``fill`` (the device count divides every axis product by
    construction, so a fill of n_devices shards cleanly)."""
    import jax

    specs = specs if isinstance(specs, (list, tuple)) else [specs]
    out = []
    for s in specs:
        shape = tuple(int(d) if (d is not None and int(d) > 0) else int(fill)
                      for d in s.shape)
        out.append(jax.ShapeDtypeStruct(  # noqa: PTA104 (host-side, never traced)
            shape, np.dtype(getattr(s, "dtype", "float32"))))
    return tuple(out)


# ---------------------------------------------------------------------- Plan
@dataclass
class Plan:
    """One candidate (and, after evaluation, scored) parallel plan."""

    mesh: Dict[str, int]                  # axis -> degree (degree>1 only)
    template: str                         # spec-template name
    stage: int = 0                        # ZeRO stage over 'sdp'
    n_devices: int = 1
    param_specs: Dict[str, List] = field(default_factory=dict)
    # -- evaluation results -------------------------------------------------
    score: float = float("inf")           # predicted step seconds
    predicted_step_ms: Optional[float] = None
    compute_ms: Optional[float] = None
    comm_ms: Optional[float] = None
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    comm_bytes: int = 0                   # est. reshard/collective bytes
    collectives: Dict[str, int] = field(default_factory=dict)
    peak_bytes: Optional[int] = None
    memory_floor_bytes: int = 0           # static state bytes / device
    feasible: bool = True
    pruned: str = ""                      # why infeasible, when not
    codes: List[str] = field(default_factory=list)  # PTA finding codes
    fingerprint: str = ""                 # collective-schedule digest
    compile_seconds: Optional[float] = None
    from_cache: bool = False              # summary came from the plan cache

    @property
    def label(self) -> str:
        mesh = "x".join(f"{a}{d}" for a, d in sorted(self.mesh.items())) or "single"
        tail = f"/zero{self.stage}" if self.stage else ""
        return f"{mesh}/{self.template}{tail}"

    def summary(self) -> Dict[str, Any]:
        """JSON-able record (the plan-cache row / bench ``plan`` payload)."""
        return {
            "label": self.label, "mesh": dict(self.mesh),
            "template": self.template, "stage": self.stage,
            "n_devices": self.n_devices, "param_specs": self.param_specs,
            "score": self.score if self.score != float("inf") else None,
            "predicted_step_ms": self.predicted_step_ms,
            "compute_ms": self.compute_ms, "comm_ms": self.comm_ms,
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "comm_bytes": self.comm_bytes, "collectives": dict(self.collectives),
            "peak_bytes": self.peak_bytes,
            "memory_floor_bytes": self.memory_floor_bytes,
            "feasible": self.feasible, "pruned": self.pruned,
            "codes": list(self.codes), "fingerprint": self.fingerprint,
            "compile_seconds": self.compile_seconds,
        }

    @classmethod
    def from_summary(cls, d: Dict[str, Any]) -> "Plan":
        plan = cls(mesh=dict(d.get("mesh") or {}),
                   template=d.get("template", "?"),
                   stage=int(d.get("stage", 0)),
                   n_devices=int(d.get("n_devices", 1)),
                   param_specs=dict(d.get("param_specs") or {}))
        plan.score = d["score"] if d.get("score") is not None else float("inf")
        for k in ("predicted_step_ms", "compute_ms", "comm_ms", "flops",
                  "bytes_accessed", "peak_bytes", "compile_seconds"):
            setattr(plan, k, d.get(k))
        plan.comm_bytes = int(d.get("comm_bytes") or 0)
        plan.collectives = dict(d.get("collectives") or {})
        plan.memory_floor_bytes = int(d.get("memory_floor_bytes") or 0)
        plan.feasible = bool(d.get("feasible", True))
        plan.pruned = d.get("pruned", "")
        plan.codes = list(d.get("codes") or [])
        plan.fingerprint = d.get("fingerprint", "")
        plan.from_cache = True
        return plan

    # ------------------------------------------------------------ builders
    def build_mesh(self, devices=None):
        """The jax Mesh this plan shards over (canonical dp/pp/sdp/mp/sep
        axis order via HybridCommunicateGroup)."""
        import jax

        from .topology import HybridCommunicateGroup

        devices = list(devices if devices is not None else jax.devices())
        if self.n_devices > len(devices):
            raise PlannerError(
                f"plan {self.label!r} needs {self.n_devices} devices, have "
                f"{len(devices)}")
        hcg = HybridCommunicateGroup(
            dp_degree=self.mesh.get("dp", 1), mp_degree=self.mesh.get("mp", 1),
            pp_degree=self.mesh.get("pp", 1),
            sharding_degree=self.mesh.get("sdp", 1),
            sep_degree=self.mesh.get("sep", 1), devices=devices)
        return hcg.mesh

    def resolved_specs(self) -> Dict[str, Any]:
        """param name -> PartitionSpec (decoded from the JSON entries)."""
        return {n: _entries_spec(e) for n, e in self.param_specs.items()}


# -------------------------------------------------------------- evaluation
def _fleet_mesh_scope(mesh):
    """Trace-time override of the global fleet mesh.

    The model forward reads ``fleet._hcg.mesh`` for its activation
    sharding constraints (gpt trunk carry pin, mp_layers ``_constraint``).
    A planner candidate evaluates on its OWN mesh, which may differ from —
    or outlive — whatever a previous ``fleet.init`` left behind; tracing
    under the global mesh then fails with incompatible device sets. This
    scope pins the constraint mesh to the candidate for the duration of a
    trace (only ``.mesh`` is read on the trace path).
    """
    import contextlib
    import types

    from .fleet import fleet as _fleet

    @contextlib.contextmanager
    def cm():
        prior = _fleet._hcg
        _fleet._hcg = types.SimpleNamespace(mesh=mesh)
        try:
            yield
        finally:
            _fleet._hcg = prior

    return cm()


def _scoped_step_fn(step, mesh):
    """``step._step`` wrapped so every TRACE of it (jit lower, scan body,
    re-specialization at dispatch time) sees the candidate mesh — not the
    global fleet state of whenever the trace happens to run."""

    def scoped_step(state, batch):
        with _fleet_mesh_scope(mesh):
            return step._step(state, batch)

    return scoped_step


def _sharded_jit(step, mesh, shardings, batch_sharding):
    """The exact jit the planner scores AND ``build_step`` dispatches —
    one construction site so the lowered program (and therefore the AOT
    executable-cache key) is identical between search and training."""
    import jax

    return jax.jit(_scoped_step_fn(step, mesh), donate_argnums=0,
                   in_shardings=(shardings, batch_sharding),
                   out_shardings=(shardings, None))


def _state_bytes_per_device(abstract_state, shardings) -> int:
    """Static per-device memory floor: the state tree's bytes after
    sharding (params + optimizer moments + buffers). The live-set peak is
    at least this — computable without lowering anything, so over-budget
    plans are pruned before a single compile."""
    from jax.tree_util import keystr, tree_flatten_with_path

    flat_sh = {keystr(p): s for p, s in tree_flatten_with_path(shardings)[0]}
    total = 0
    for path, leaf in tree_flatten_with_path(abstract_state)[0]:  # noqa: PTA102 (host-side, never traced)
        try:
            itemsize = np.dtype(leaf.dtype).itemsize
        except (TypeError, AttributeError):
            continue  # typed PRNG keys etc. — negligible  # noqa: PTA103 (host-side, never traced)
        shape = tuple(leaf.shape)
        sh = flat_sh.get(keystr(path))
        if sh is not None:
            try:
                shape = sh.shard_shape(shape)
            except Exception:
                pass
        total += int(np.prod(shape)) * itemsize
    return total


def _evaluate(plan: Plan, step, abstract_state, abstract_batch, devices,
              budget_mb: float, hw, options) -> Plan:
    """Score one candidate from shapes alone: lower + compile under the
    candidate shardings (AOT — nothing dispatched), run the SPMD analyzer,
    price the verdict with the roofline."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..analysis import spmd as _spmd
    from ..cost_model import predict_step_time
    from ..observability.introspect import aot_compile
    from ..observability.metrics import counter_inc
    from .sharding import state_shardings

    counter_inc("planner.evaluations")
    mesh = plan.build_mesh(devices)
    mp_specs = plan.resolved_specs()
    shardings = state_shardings(step.state, mesh, stage=plan.stage,
                                mp_specs=mp_specs)
    plan.memory_floor_bytes = _state_bytes_per_device(abstract_state, shardings)
    if budget_mb and plan.memory_floor_bytes > budget_mb * (1 << 20):
        # PTA204 applied pre-flight: the state alone cannot fit, no point
        # paying a compile to learn the peak is even higher
        plan.feasible = False  # noqa: PTA104 (host-side, never traced)
        plan.pruned = (f"PTA204: static state floor "  # noqa: PTA104 (host-side, never traced)
                       f"~{plan.memory_floor_bytes / (1 << 20):.1f} MiB/device "
                       f"exceeds FLAGS_hbm_budget_mb={budget_mb:g}")
        counter_inc("planner.pruned")
        return plan
    batch_sharding = NamedSharding(mesh, P(("dp", "sdp")))
    jitted = _sharded_jit(step, mesh, shardings, batch_sharding)
    compiled, info = aot_compile(jitted, (abstract_state, abstract_batch),
                                 cache_scope="train_step")
    plan.compile_seconds = info.get("compile_seconds")
    if compiled is None:
        plan.feasible = False  # noqa: PTA104 (host-side, never traced)
        plan.pruned = f"lower/compile failed: {info.get('aot_error', '?')}"  # noqa: PTA104 (host-side, never traced)
        counter_inc("planner.pruned")
        return plan
    opts = _spmd.ShardCheckOptions(
        hbm_budget_mb=budget_mb,
        allgather_warn_bytes=getattr(options, "allgather_warn_bytes", 1 << 20)
        if options is not None else 1 << 20)
    report = _spmd.analyze_compiled(
        compiled, label=plan.label, kind="plan", options=opts,
        params=abstract_state.get("params"),
        param_shardings=shardings.get("params"))
    plan.comm_bytes = report.moved_bytes
    plan.collectives = report.counts()
    plan.fingerprint = report.fingerprint
    plan.codes = sorted({d.code for d in report.diagnostics})
    plan.flops = info.get("flops")
    plan.bytes_accessed = info.get("bytes_accessed")
    plan.peak_bytes = report.peak_bytes
    if plan.peak_bytes is None:
        try:  # text-only floor when the backend reports no memory stats
            from ..analysis import hlo as _hlo

            plan.peak_bytes = _hlo.entry_memory_lower_bound(compiled.as_text())  # noqa: PTA104 (host-side, never traced)
        except Exception:
            plan.peak_bytes = None  # noqa: PTA104 (host-side, never traced)
    plan.feasible = not report.errors
    if report.errors:
        plan.pruned = "; ".join(f"{d.code}" for d in report.errors)  # noqa: PTA104 (host-side, never traced)
        counter_inc("planner.pruned")
    pred = predict_step_time(plan.flops, plan.bytes_accessed,
                             plan.comm_bytes, hw=hw)
    plan.score = pred["total_s"]
    plan.predicted_step_ms = pred["total_s"] * 1e3
    plan.compute_ms = max(pred["compute_s"], pred["memory_s"]) * 1e3
    plan.comm_ms = pred["comm_s"] * 1e3
    del compiled  # the executable (if cached) lives in the AOT store
    return plan


# ------------------------------------------------------------------- cache
def _model_fingerprint(model) -> str:
    rows = [type(model).__name__]
    for n, p in sorted(model.named_parameters()):  # noqa: PTA102 (host-side, never traced)
        dt = getattr(p, "dtype", None) or getattr(p._value, "dtype", "?")
        spec = getattr(p, "dist_spec", None)
        rows.append(f"{n}:{tuple(p.shape)}:{dt}:{spec}")  # noqa: PTA104 (host-side, never traced)
    return hashlib.sha256("|".join(rows).encode()).hexdigest()[:16]


def _cache_path(key: str):
    from ..framework.flags import flag

    d = flag("FLAGS_compile_cache_dir")
    if not d:
        return None
    return os.path.join(str(d), "planner", f"{key}.json")


def _cache_key(model, n_devices, abstract_batch, template_names, stages,
               axes, meshes, budget_mb) -> str:
    import jax

    shapes = [f"{l.dtype}{list(l.shape)}"
              for l in _tree_leaves_safe(abstract_batch)]
    payload = repr(("plan-v1", _model_fingerprint(model), int(n_devices),
                    shapes, sorted(template_names), tuple(stages),
                    tuple(axes), meshes, float(budget_mb or 0),
                    jax.__version__, jax.default_backend()))
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def _tree_leaves_safe(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


# ------------------------------------------------------------------ search
def search(model, n_devices: int, *, inputs_spec, labels_spec=None,
           loss=None, optimizer=None, templates=None, meshes=None,
           stages: Sequence[int] = (2,), axes: Sequence[str] = DEFAULT_AXES,
           options=None, cache: bool = True, max_candidates: int = 32,
           devices=None, hw=None, seed: int = 0) -> List[Plan]:
    """Rank parallel plans for ``model`` on ``n_devices`` from shapes alone.

    ``inputs_spec``/``labels_spec`` are ``static.InputSpec``s (or anything
    with shape/dtype); dynamic dims are probed at the device count. ``loss``
    is required (the scored program is the full fwd+bwd+update step);
    ``optimizer`` defaults to AdamW. ``templates`` maps name ->
    {param: PartitionSpec} (or a callable of the model); default is the
    model's own annotations plus fully-replicated. ``meshes`` overrides the
    axis-factorization enumeration with an explicit candidate list.
    ``stages`` are the ZeRO stages tried when a candidate mesh has sdp > 1.

    Nothing is dispatched: every candidate is lowered+compiled on
    ``ShapeDtypeStruct``s and scored from the SpmdReport + cost analysis.
    Returns plans ranked best-first (feasible before infeasible, then
    predicted step time). With ``FLAGS_compile_cache_dir`` set the ranked
    list round-trips through the on-disk plan cache — a restart with the
    same (model, device count, shapes) pays zero search.
    """
    import jax

    from ..observability import runlog as _runlog
    from ..observability import span as _span
    from ..observability.metrics import counter_inc

    t0 = time.perf_counter()
    counter_inc("planner.searches")
    if inputs_spec is None:
        raise PlannerError("search needs inputs_spec (shapes are the input)")
    if loss is None:
        raise PlannerError("search needs loss (it scores the full training "
                           "step, not just the forward)")
    devices = list(devices if devices is not None else jax.devices())
    if int(n_devices) > len(devices):
        raise PlannerError(
            f"search over {n_devices} devices but only {len(devices)} are "
            "visible (CPU dryrun: XLA_FLAGS=--xla_force_host_platform_"
            "device_count=N)")
    n_devices = int(n_devices)

    # resolve the spec-template set
    if templates is None:
        templates = default_templates(model)
    resolved: Dict[str, Dict[str, Any]] = {}
    for name, t in templates.items():  # noqa: PTA102 (host-side, never traced)
        specs = t(model) if callable(t) else dict(t or {})
        resolved[name] = {k: _spec_entries(v) for k, v in specs.items()}  # noqa: PTA104 (host-side, never traced)

    mesh_list = list(meshes) if meshes is not None else mesh_shapes(n_devices, axes)
    candidates: List[Plan] = []
    for m in mesh_list:
        degrees = {a: int(d) for a, d in m.items() if int(d) > 1}
        need = int(np.prod(list(degrees.values()))) if degrees else 1
        if need != n_devices:
            raise PlannerError(
                f"mesh candidate {m} covers {need} devices, expected "
                f"{n_devices}")
        cand_stages = tuple(stages) if degrees.get("sdp", 1) > 1 else (0,)
        for tname in resolved:
            for stage in cand_stages:
                candidates.append(Plan(mesh=degrees, template=tname,  # noqa: PTA104 (host-side, never traced)
                                       stage=int(stage), n_devices=n_devices,
                                       param_specs=resolved[tname]))
    dropped = max(0, len(candidates) - int(max_candidates))
    candidates = candidates[:int(max_candidates)]
    counter_inc("planner.candidates", len(candidates))

    budget_mb = _budget_mb(options)

    # plan cache: a restart with the same key pays zero search
    key = _cache_key(model, n_devices,
                     abstract_inputs(inputs_spec, n_devices),
                     sorted(resolved), stages, axes,
                     sorted(tuple(sorted(m.items())) for m in mesh_list),
                     budget_mb)
    path = _cache_path(key) if cache else None
    if path is not None and os.path.exists(path):
        try:
            with open(path) as f:
                payload = json.load(f)
            plans = [Plan.from_summary(d) for d in payload["plans"]]
            counter_inc("planner.cache_hits")
            _runlog.emit("plan", devices=n_devices, candidates=len(plans),
                         cached=True, search_ms=round(
                             (time.perf_counter() - t0) * 1e3, 3),
                         chosen=plans[0].summary() if plans else None)
            return plans
        except Exception:
            pass  # unreadable cache file: fall through to a live search

    # one TrainStep build gives the state tree; everything after is abstract
    from ..jit import TrainStep

    if optimizer is None:
        from .. import optimizer as _optim

        optimizer = _optim.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, optimizer, loss, seed=seed)
    abstract_state = jax.eval_shape(lambda s: s, step.state)
    abstract_batch = (abstract_inputs(inputs_spec, n_devices),
                      abstract_inputs(labels_spec if labels_spec is not None
                                      else inputs_spec, n_devices))
    if hw is None:
        from ..cost_model import hardware_spec

        hw = hardware_spec()

    with _span("planner.search"):
        for plan in candidates:
            try:
                _evaluate(plan, step, abstract_state, abstract_batch,
                          devices, budget_mb, hw, options)
            except Exception as exc:  # a broken candidate must not kill search
                plan.feasible = False  # noqa: PTA104 (host-side, never traced)
                plan.pruned = f"evaluation failed: {type(exc).__name__}: {exc}"  # noqa: PTA104 (host-side, never traced)
                counter_inc("planner.pruned")

    plans = sorted(candidates,
                   key=lambda p: (not p.feasible, p.score, p.comm_bytes,
                                  p.memory_floor_bytes))
    if path is not None:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump({"format": 1, "devices": n_devices,
                           "plans": [p.summary() for p in plans]}, f)
            os.replace(tmp, path)
            counter_inc("planner.cache_stores")
        except OSError:
            pass
    search_ms = round((time.perf_counter() - t0) * 1e3, 3)
    _runlog.emit("plan", devices=n_devices, candidates=len(candidates),
                 dropped=dropped, cached=False, search_ms=search_ms,
                 pruned=sum(1 for p in plans if not p.feasible),
                 chosen=plans[0].summary() if plans else None)
    return plans


def _budget_mb(options) -> float:
    if options is not None and getattr(options, "hbm_budget_mb", None) is not None:
        return float(options.hbm_budget_mb)
    from ..framework.flags import flag

    return float(flag("FLAGS_hbm_budget_mb"))


# ----------------------------------------------------------------- builders
def build_step(model, optimizer, loss_fn, plan: Plan, devices=None,
               seed: int = 0, **step_kwargs):
    """A sharded ``jit.TrainStep`` executing ``plan`` — the fleet
    ``distributed_step`` assembly driven by a searched plan instead of
    hand-picked strategy knobs. The dispatch jit is built by the same
    helper the planner scored with, so a plan evaluated during an elastic
    HOLD window resumes on an already-cached executable."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..jit import TrainStep, scan_steps
    from .sharding import place_state, state_shardings

    mesh = plan.build_mesh(devices)
    step = TrainStep(model, optimizer, loss_fn, seed=seed, **step_kwargs)
    shardings = state_shardings(step.state, mesh, stage=plan.stage,
                                mp_specs=plan.resolved_specs())
    batch_sharding = NamedSharding(mesh, P(("dp", "sdp")))
    step.mesh = mesh
    step.state = place_state(step.state, shardings)
    step._jit = _sharded_jit(step, mesh, shardings, batch_sharding)
    step._jit_multi = scan_steps(_scoped_step_fn(step, mesh), donate_argnums=0,
                                 in_shardings=(shardings, None),
                                 out_shardings=(shardings, None))
    step.state_shardings = shardings
    step._state_shardings = shardings
    step.plan = plan
    return step


def elastic_replan(model, optimizer_factory: Callable[[], Any], loss_fn, *,
                   inputs_spec, labels_spec=None,
                   devices_for: Callable[[List[int]], int],
                   on_step: Optional[Callable[[Any], None]] = None,
                   seed: int = 0, **search_kw):
    """An ``on_rescale`` hook for :func:`~.resilience.run_resilient`:
    when membership settles on a different node set, re-plan for the new
    device count (plan-cache hit when this topology was seen before),
    build the sharded TrainStep for the winning plan — compiling it *now*,
    during the HOLD window, into the AOT executable cache — and hand the
    supervisor the new state template + shardings so the checkpoint
    restores resharded onto the new mesh.

    ``devices_for(members)`` maps the settled member list to a device
    count; ``on_step(train_step)`` receives each freshly built TrainStep
    (rebind your training closure there). The returned hook gives
    ``run_resilient`` ``(savable_target, savable_shardings)``.
    """
    from ..stability import state_to_savable

    def on_rescale(members, _state):
        n = int(devices_for(members))
        plans = search(model, n, inputs_spec=inputs_spec,
                       labels_spec=labels_spec, loss=loss_fn,
                       optimizer=optimizer_factory(), seed=seed, **search_kw)
        best = next((p for p in plans if p.feasible), None)
        if best is None:
            raise PlannerError(
                f"no feasible plan for {n} device(s): "
                + "; ".join(f"{p.label}: {p.pruned}" for p in plans))
        step = build_step(model, optimizer_factory(), loss_fn, best, seed=seed)
        if on_step is not None:
            on_step(step)
        target = state_to_savable(step.state)
        shardings = dict(step._state_shardings)
        # the savable rng is raw key data; its replicated spec still applies
        return target, shardings

    return on_rescale


# --------------------------------------------------------------------- CLI
def format_plan_table(plans: List[Plan]) -> str:
    header = ["plan", "ok", "pred ms", "comm MB/step", "peak MiB",
              "state MiB", "codes"]
    body = []
    for p in plans:
        body.append([  # noqa: PTA104 (host-side, never traced)
            p.label,
            "yes" if p.feasible else f"NO ({p.pruned[:40]})",
            "-" if p.predicted_step_ms is None else f"{p.predicted_step_ms:.3f}",
            f"{p.comm_bytes / 1e6:.3f}",
            "-" if p.peak_bytes is None else f"{p.peak_bytes / (1 << 20):.1f}",
            f"{p.memory_floor_bytes / (1 << 20):.1f}",
            ",".join(p.codes) or "-",
        ])
    widths = [max(len(r[i]) for r in [header] + body) for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*["-" * w for w in widths])]
    lines += [fmt.format(*r) for r in body]
    return "\n".join(lines)


def main(argv=None) -> int:
    """``python -m paddle_tpu.distributed.planner --devices N [--json]`` —
    rank parallel plans for a GPT model (tiny by default) on N devices."""
    import argparse

    p = argparse.ArgumentParser(prog="python -m paddle_tpu.distributed.planner")
    p.add_argument("--devices", type=int, default=0,
                   help="device count to plan for (default: all visible)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--axes", default=",".join(DEFAULT_AXES),
                   help="comma list of mesh axes to factor over")
    p.add_argument("--stage", type=int, default=2,
                   help="ZeRO stage tried when sdp > 1")
    p.add_argument("--hbm-budget", type=float, default=None,
                   help="per-device MiB budget (PTA204 pruning)")
    p.add_argument("--no-cache", action="store_true",
                   help="skip the FLAGS_compile_cache_dir plan cache")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    import sys

    import jax

    import paddle_tpu as paddle
    from ..models.gpt import (
        GPTConfig,
        GPTForPretraining,
        GPTPretrainingCriterion,
    )

    n = args.devices or len(jax.devices())
    if n > len(jax.devices()):
        print(f"planner: {n} devices requested, {len(jax.devices())} visible "  # noqa: PTA105 (host-side, never traced)
              "(CPU dryrun: XLA_FLAGS=--xla_force_host_platform_device_count"
              f"={n})", file=sys.stderr)
        return 2
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_seq_len=max(args.seq, 2 * args.seq))
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    from ..analysis.spmd import ShardCheckOptions

    options = (ShardCheckOptions(hbm_budget_mb=args.hbm_budget)
               if args.hbm_budget is not None else None)
    spec = jax.ShapeDtypeStruct((args.batch, args.seq), np.int32)
    plans = search(model, n, inputs_spec=spec, loss=GPTPretrainingCriterion(),
                   optimizer=opt, axes=tuple(args.axes.split(",")),
                   stages=(args.stage,), options=options,
                   cache=not args.no_cache)
    if args.json:
        print(json.dumps([pl.summary() for pl in plans], indent=2))  # noqa: PTA105 (host-side, never traced)
    else:
        print(f"ranked plans for {n} device(s) "  # noqa: PTA105 (host-side, never traced)
              f"(backend: {jax.default_backend()}):")
        print(format_plan_table(plans))  # noqa: PTA105 (host-side, never traced)
    return 0 if any(pl.feasible for pl in plans) else 1


if __name__ == "__main__":
    raise SystemExit(main())
