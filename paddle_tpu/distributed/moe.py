"""Mixture-of-Experts / expert parallelism (parity:
python/paddle/incubate/distributed/models/moe/moe_layer.py + gates
moe/gate/{naive,gshard,switch}_gate.py; dispatch via global_scatter/
global_gather ops, operators/collective/global_scatter_op.cu.cc).

TPU-first: routing (gate scores, top-k, GShard random second-expert
jitter, capacity dropping, aux loss) happens here; the dispatch/expert-FFN/
combine core goes through the :mod:`paddle_tpu.ops.registry` ``moe``
kernel — the fused sort-based Pallas implementation
(:mod:`paddle_tpu.ops.moe_pallas`) when available, else the ``dense``
GShard-style composite below (one-hot + cumsum dispatch einsums, whose
sharded-expert einsum compiles to the all-to-all the reference implements
as count-aware NCCL alltoall). Capacity-dropping keeps shapes static (the
XLA contract).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework import random as _random
from ..nn import initializer as I
from ..nn.layer.base import Layer
from ..ops import moe_pallas as _moe_pallas  # noqa: F401 — registers 'pallas_sorted'
from ..ops import registry as _registry
from ..tensor._helpers import ensure_tensor, op


class NaiveGate(Layer):
    """moe/gate/naive_gate.py: linear scores + top-k. No jitter, no aux
    loss, no capacity opinion (``capacity = None`` defers to the layer)."""

    capacity = None
    random_routing = False

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        self.topk = topk
        self.num_expert = num_expert
        self.weight = self.create_parameter([d_model, num_expert], default_initializer=I.XavierNormal())

    def score(self, x_val):
        return x_val @ self.weight._value

    @staticmethod
    def aux_loss(probs, gate_idx, num_expert):
        return jnp.zeros((), probs.dtype)


class GShardGate(NaiveGate):
    """moe/gate/gshard_gate.py: top-2 with random second-expert jitter +
    the GShard load-balance aux loss.

    ``capacity`` is the (train, eval) capacity-factor pair the layer's
    capacity computation uses when no explicit factor is given. Train-time
    ``random_routing`` keeps each token's second expert with probability
    ``min(1, 2·p2)`` (the reference's ``2*topk_val > rand`` test); a
    dropped pair is simply not dispatched and consumes no capacity. Off in
    eval, rng via :mod:`paddle_tpu.framework.random`.
    """

    def __init__(self, d_model, num_expert, world_size=1, topk=2, capacity=(1.2, 2.4), random_routing=True):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = tuple(capacity)
        self.random_routing = bool(random_routing)

    @staticmethod
    def aux_loss(probs, gate_idx, num_expert):
        # GShard eq.4: mean gate prob * top-1 dispatch fraction, scaled by E
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], num_expert, dtype=probs.dtype), axis=0)
        return num_expert * jnp.sum(me * ce)


class SwitchGate(NaiveGate):
    """moe/gate/switch_gate.py: top-1 routing; Switch-Transformer aux loss
    (same E·Σ me·ce form over the top-1 assignment)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1, capacity=(1.2, 2.4)):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = tuple(capacity)

    aux_loss = staticmethod(GShardGate.aux_loss)


def dense_dispatch_combine(tokens, gate_vals, gate_idx, drop_mask, w1, b1, w2, b2, *,
                           capacity, activation):
    """GShard/Switch-lineage dense composite: one-hot + cumsum queue
    positions, padded [E, capacity, D] dispatch einsums, gather combine.
    The registry's ``moe`` fallback — always available, and the numerical
    reference the Pallas path is pinned against."""
    T, D = tokens.shape
    E = w1.shape[0]
    K = gate_idx.shape[1]

    flat_idx = gate_idx.reshape(-1)  # [T*K] expert ids (k-major per token)
    onehot = jax.nn.one_hot(flat_idx, E, dtype=jnp.int32)  # [T*K, E]
    if drop_mask is not None:
        # jitter-dropped pairs are not dispatched and consume no capacity
        onehot = onehot * (1 - drop_mask.reshape(-1).astype(jnp.int32))[:, None]
    pos_in_expert = jnp.cumsum(onehot, axis=0) - 1  # rank within expert
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [T*K]
    keep = pos < capacity
    if drop_mask is not None:
        keep = keep & ~drop_mask.reshape(-1)
    gv = gate_vals.reshape(-1) * keep.astype(gate_vals.dtype)

    # dispatch: [E, capacity, D]
    disp = jnp.zeros((E, capacity, D), tokens.dtype)
    tok_rep = jnp.repeat(jnp.arange(T), K)
    e_ids = jnp.where(keep, flat_idx, 0)
    p_ids = jnp.where(keep, pos, 0)
    contrib = tokens[tok_rep] * keep[:, None].astype(tokens.dtype)
    disp = disp.at[e_ids, p_ids].add(contrib)

    # expert FFN, batched over E — one big MXU matmul per projection
    h = activation(jnp.einsum("ecd,edh->ech", disp, w1) + b1)
    y = jnp.einsum("ech,ehd->ecd", h, w2) + b2

    # combine back: weighted gather
    gathered = y[e_ids, p_ids]  # [T*K, D]
    combined = jnp.zeros((T, D), y.dtype)
    return combined.at[tok_rep].add(gathered * gv[:, None])


_registry.register(
    "moe", "dense", dense_dispatch_combine, fallback=True,
    doc="one-hot/cumsum dispatch + padded [E,capacity,D] einsums (XLA composite)")


class MoELayer(Layer):
    """Expert-parallel FFN MoE.

    experts: stacked FFN weights [E, ...] with dist_spec over the expert axis.
    gate: 'naive' | 'gshard' | 'switch' (reference moe_layer.py gate arg).
    capacity_factor: explicit per-expert capacity factor; ``None`` (default)
    routes the gate's ``capacity`` (train, eval) pair into the capacity
    computation — GShard/Switch default to (1.2, 2.4).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, capacity_factor=None, gate="gshard", expert_axis="dp", activation="gelu", name=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        gate_cls = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}[gate]
        self.gate = gate_cls(d_model, num_experts, topk=self.top_k)
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden], default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model], default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        for p, spec in ((self.w1, P(expert_axis, None, None)), (self.b1, P(expert_axis, None, None)), (self.w2, P(expert_axis, None, None)), (self.b2, P(expert_axis, None, None))):
            p.dist_spec = spec
            p.is_distributed = True
        self.aux_loss = None

    def _capacity_factor(self):
        if self.capacity_factor is not None:
            return float(self.capacity_factor)
        cap = getattr(self.gate, "capacity", None) or (1.25, 2.0)
        return float(cap[0] if self.training else cap[1])

    def forward(self, x):
        """x: [batch, seq, d_model] (or [tokens, d_model])."""
        x = ensure_tensor(x)
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu}[self.activation]
        E, K, cf = self.num_experts, self.top_k, self._capacity_factor()
        jitter = bool(self.training and getattr(self.gate, "random_routing", False) and K >= 2)
        gate_aux = type(self.gate).aux_loss
        aux_in = [_random.key_tensor()] if jitter else []

        def fn(xv, gate_w, w1, b1, w2, b2, *extra):
            xs = xv if xv.ndim == 3 else xv[None]
            B, S, D = xs.shape
            tokens = xs.reshape(B * S, D)
            n_tok = B * S
            capacity = max(1, int(math.ceil(n_tok * K * cf / E)))

            logits = tokens @ gate_w  # [T, E]
            probs = jax.nn.softmax(logits, axis=-1)
            gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [T, K]

            drop_mask = None
            if jitter:
                # GShard random routing: keep the 2nd expert with
                # probability min(1, 2·p2); other ranks always dispatch
                r = jax.random.uniform(jax.random.fold_in(extra[0], 0), (n_tok,), gate_vals.dtype)
                drop2 = 2.0 * gate_vals[:, 1] <= r
                drop_mask = jnp.zeros((n_tok, K), bool).at[:, 1].set(drop2)

            aux = gate_aux(probs, gate_idx, E)
            out = _registry.dispatch(
                "moe", tokens, gate_vals, gate_idx, drop_mask, w1, b1, w2, b2,
                capacity=capacity, activation=act)
            out = out.reshape(B, S, D)
            return (out[0] if xv.ndim == 2 else out), aux

        out, aux = op(fn, x, self.gate.weight, self.w1, self.b1, self.w2, self.b2, *aux_in, _name="moe")
        self.aux_loss = aux
        return out
