"""Mixture-of-Experts / expert parallelism (parity:
python/paddle/incubate/distributed/models/moe/moe_layer.py + gates
moe/gate/{naive,gshard,switch}_gate.py; dispatch via global_scatter/
global_gather ops, operators/collective/global_scatter_op.cu.cc).

TPU-first: GShard-style dense dispatch/combine einsums with expert weights
stacked on a leading axis sharded over the expert mesh axis. Under pjit the
dispatch einsum against the sharded expert dim compiles to the all-to-all
the reference implements as count-aware NCCL alltoall; capacity-dropping
keeps shapes static (the XLA contract).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework import random as _random
from ..nn import functional as Fnn
from ..nn import initializer as I
from ..nn.layer.base import Layer
from ..tensor._helpers import ensure_tensor, op


class NaiveGate(Layer):
    """moe/gate/naive_gate.py: linear scores + top-k."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        self.topk = topk
        self.num_expert = num_expert
        self.weight = self.create_parameter([d_model, num_expert], default_initializer=I.XavierNormal())

    def score(self, x_val):
        return x_val @ self.weight._value


class GShardGate(NaiveGate):
    """moe/gate/gshard_gate.py: top-2 with random second-expert jitter +
    aux load-balance loss."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2, capacity=(1.2, 2.4)):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = capacity


class SwitchGate(NaiveGate):
    """moe/gate/switch_gate.py: top-1 routing."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1, capacity=(1.2, 2.4)):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity = capacity


class MoELayer(Layer):
    """Expert-parallel FFN MoE.

    experts: stacked FFN weights [E, ...] with dist_spec over the expert axis.
    gate: 'naive' | 'gshard' | 'switch' (reference moe_layer.py gate arg).
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, capacity_factor=1.25, gate="gshard", expert_axis="dp", activation="gelu", name=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.activation = activation
        gate_cls = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}[gate]
        self.gate = gate_cls(d_model, num_experts, topk=self.top_k)
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden], default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model], default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        for p, spec in ((self.w1, P(expert_axis, None, None)), (self.b1, P(expert_axis, None, None)), (self.w2, P(expert_axis, None, None)), (self.b2, P(expert_axis, None, None))):
            p.dist_spec = spec
            p.is_distributed = True
        self.aux_loss = None

    def forward(self, x):
        """x: [batch, seq, d_model] (or [tokens, d_model])."""
        x = ensure_tensor(x)
        squeeze_back = x.ndim == 2
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu}[self.activation]
        E, K, cf = self.num_experts, self.top_k, self.capacity_factor

        def fn(xv, gate_w, w1, b1, w2, b2):
            xs = xv if xv.ndim == 3 else xv[None]
            B, S, D = xs.shape
            tokens = xs.reshape(B * S, D)
            n_tok = B * S
            capacity = max(1, int(math.ceil(n_tok * K * cf / E)))

            logits = tokens @ gate_w  # [T, E]
            probs = jax.nn.softmax(logits, axis=-1)
            gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [T, K]

            # aux load-balance loss (GShard eq.4): mean prob * token fraction
            me = jnp.mean(probs, axis=0)
            one_hot_top1 = jax.nn.one_hot(gate_idx[:, 0], E)
            ce = jnp.mean(one_hot_top1, axis=0)
            aux = E * jnp.sum(me * ce)

            # position of each (token, k) within its expert queue
            flat_idx = gate_idx.reshape(-1)  # [T*K] expert ids (k-major per token)
            onehot = jax.nn.one_hot(flat_idx, E, dtype=jnp.int32)  # [T*K, E]
            pos_in_expert = jnp.cumsum(onehot, axis=0) - 1  # rank within expert
            pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [T*K]
            keep = pos < capacity
            gv = gate_vals.reshape(-1) * keep.astype(gate_vals.dtype)

            # dispatch: [E, capacity, D]
            disp = jnp.zeros((E, capacity, D), tokens.dtype)
            tok_rep = jnp.repeat(jnp.arange(n_tok), K)
            e_ids = jnp.where(keep, flat_idx, 0)
            p_ids = jnp.where(keep, pos, 0)
            contrib = tokens[tok_rep] * keep[:, None].astype(tokens.dtype)
            disp = disp.at[e_ids, p_ids].add(contrib)

            # expert FFN, batched over E — one big MXU matmul per projection
            h = act(jnp.einsum("ecd,edh->ech", disp, w1) + b1)
            y = jnp.einsum("ech,ehd->ecd", h, w2) + b2

            # combine back: weighted gather
            gathered = y[e_ids, p_ids]  # [T*K, D]
            combined = jnp.zeros((n_tok, D), y.dtype)
            combined = combined.at[tok_rep].add(gathered * gv[:, None])
            out = combined.reshape(B, S, D)
            return (out[0] if xv.ndim == 2 else out), aux

        out, aux = op(fn, x, self.gate.weight, self.w1, self.b1, self.w2, self.b2, _name="moe")
        self.aux_loss = aux
        return out
