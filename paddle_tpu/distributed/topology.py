"""Hybrid topology (parity: python/paddle/distributed/fleet/base/topology.py
— CommunicateTopology:52 + HybridCommunicateGroup:134).

TPU-first: the 4-D rank grid *is* a ``jax.sharding.Mesh`` with named axes in
the reference's canonical order data→pipe→sharding→model (+ 'sep' for the
green-field sequence axis). "Communication groups" are mesh axis names —
XLA's partitioner emits the collectives; no NCCL comm construction
(reference new_group → ProcessGroupNCCL) is needed.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES = ("dp", "pp", "sdp", "mp", "sep")


class CommunicateTopology:
    def __init__(self, hybrid_group_names: Sequence[str] = AXES, dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))


class HybridCommunicateGroup:
    """Builds the device mesh and exposes paddle-fleet style accessors."""

    def __init__(self, dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1, sep_degree=1, devices: Optional[List] = None):
        devices = list(devices if devices is not None else jax.devices())
        need = dp_degree * mp_degree * pp_degree * sharding_degree * sep_degree
        if need > len(devices):
            raise ValueError(f"hybrid degrees need {need} devices, have {len(devices)}")
        devices = devices[:need]
        self.dims = (dp_degree, pp_degree, sharding_degree, mp_degree, sep_degree)
        grid = np.array(devices).reshape(self.dims)
        self.mesh = Mesh(grid, AXES)
        self.topo = CommunicateTopology(AXES, self.dims)

    # paddle fleet accessors (fleet/base/topology.py:169-260)
    def get_data_parallel_world_size(self):
        return self.dims[0]

    def get_pipe_parallel_world_size(self):
        return self.dims[1]

    def get_sharding_parallel_world_size(self):
        return self.dims[2]

    def get_model_parallel_world_size(self):
        return self.dims[3]

    def get_sep_parallel_world_size(self):
        return self.dims[4]

    def get_data_parallel_rank(self):
        return 0  # single controller: per-device ranks are mesh coords

    def get_model_parallel_group(self):
        return "mp"

    def get_data_parallel_group(self):
        return "dp"

    def get_pipe_parallel_group(self):
        return "pp"

    def get_sharding_parallel_group(self):
        return "sdp"

    def get_sep_parallel_group(self):
        return "sep"

    # -- sharding helpers --------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def batch_sharding(self) -> NamedSharding:
        """Global-batch axis sharded over data-like axes (dp × sdp)."""
        return NamedSharding(self.mesh, PartitionSpec(("dp", "sdp")))


def build_mesh(dp=1, mp=1, pp=1, sdp=1, sep=1, devices=None) -> Mesh:
    return HybridCommunicateGroup(dp, mp, pp, sdp, sep, devices).mesh
