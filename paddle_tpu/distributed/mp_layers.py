"""Tensor-parallel layers (parity:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding:30, ColumnParallelLinear:95, RowParallelLinear:171,
ParallelCrossEntropy:251).

TPU-first: these are *sharding-annotated* layers. The weight carries a
PartitionSpec on the 'mp' mesh axis (consumed by the jit path's GSPMD
partitioner) and the forward inserts sharding constraints; XLA emits the
all-reduce/all-gather the reference hand-writes with c_* collectives
(c_allreduce in RowParallelLinear, c_softmax_with_cross_entropy for the
vocab-parallel loss, operators/collective/c_softmax_with_cross_entropy_op.cu:139).
Single-device eager runs ignore the specs — same numerics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.base import Layer
from ..tensor._helpers import ensure_tensor, op


def _act_spec(mesh, ndim, last):
    """Activation PartitionSpec: batch over the data axes (dp×sdp), seq over
    'sep' when sequence parallelism is on, feature dim per ``last``. Keeping
    batch sharded here is what lets GSPMD compose TP with DP without
    rematerializing activations."""
    dims = [None] * ndim
    if ndim >= 2:
        dims[0] = ("dp", "sdp")
    if ndim >= 3 and mesh.shape.get("sep", 1) > 1:
        dims[1] = "sep"
    dims[-1] = last
    return P(*dims)


def _constraint(x_val, last):
    """Constrain an activation's sharding if a fleet mesh is active.
    ``last`` is the spec entry for the trailing (feature) dim."""
    from .fleet import fleet

    if fleet._hcg is None:
        return x_val
    mesh = fleet._hcg.mesh
    if mesh.shape.get("mp", 1) == 1:
        return x_val
    spec = _act_spec(mesh, x_val.ndim, last)
    try:
        return jax.lax.with_sharding_constraint(x_val, NamedSharding(mesh, spec))
    except ValueError:
        # eager (uncommitted to mesh) — constraint only matters under jit
        return x_val


class ColumnParallelLinear(Layer):
    """y = x @ W, W [in, out] sharded on out over 'mp'."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True, gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr, default_initializer=I.XavierNormal())
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = True
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_spec = P("mp")
            self.bias.is_distributed = True

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        out._value = _constraint(out._value, None if self.gather_output else "mp")
        return out


class RowParallelLinear(Layer):
    """y = x @ W, W [in, out] sharded on in over 'mp'; XLA inserts the
    all-reduce the reference does manually (mp_layers.py:171)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True, input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr, default_initializer=I.XavierNormal())
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)

    def forward(self, x):
        if self.input_is_parallel:
            x = ensure_tensor(x)
            x._value = _constraint(x._value, "mp")
        out = F.linear(x, self.weight, self.bias)
        out._value = _constraint(out._value, None)
        return out


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp' (mp_layers.py:30)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr, default_initializer=I.Normal(0.0, 0.02))
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE: annotate logits sharded on the class axis;
    GSPMD partitions the softmax reductions (the
    c_softmax_with_cross_entropy kernel's job)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = ensure_tensor(input)
        input._value = _constraint(input._value, "mp")
        return F.cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)


class TensorParallel(Layer):
    """Wrapper parity (fleet/meta_parallel/tensor_parallel.py:25): on TPU the
    wrapped model needs no broadcast/param-sync — the single controller owns
    one copy of every param; it simply marks the model as mp-annotated."""

    def __init__(self, layers, hcg=None, **kwargs):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


class _RNGStatesTracker:
    """Named RNG streams (parity: parallel_layers/random.py RNGStatesTracker).

    ``rng_state(name)`` is a context under which dropout draws come from the
    named stream's own JAX key chain — the reference keeps per-name CUDA RNG
    states so mp-sharded dropout is identical across tensor-parallel ranks
    while local dropout differs; with explicit JAX keys a stream is just a
    seeded key we fold a call-counter into."""

    def __init__(self):
        import jax

        self._jax = jax
        self._seeds = {}
        self._counts = {}

    def add(self, name, seed):
        if name in self._seeds and self._seeds[name] != int(seed):
            raise ValueError(f"RNG stream {name!r} already added with a different seed")
        self._seeds[name] = int(seed)
        self._counts.setdefault(name, 0)

    def get_states_tracker(self):
        return dict(self._seeds)

    def rng_state(self, name="model_parallel_rng"):
        from ..framework import random as _random

        if name not in self._seeds:
            raise ValueError(f"unknown RNG stream {name!r}; call add(name, seed) first")
        self._counts[name] += 1
        key = self._jax.random.fold_in(
            self._jax.random.key(self._seeds[name]), self._counts[name])
        return _random.rng_scope(key)


_RNG_TRACKER = None


def get_rng_state_tracker():
    global _RNG_TRACKER
    if _RNG_TRACKER is None:
        _RNG_TRACKER = _RNGStatesTracker()
    return _RNG_TRACKER
