"""Cross-mesh checkpoint conversion (parity: auto_parallel/converter.py —
SURVEY §7's named "hard part": restore a checkpoint saved under mesh A onto
mesh B).

The reference converter slices/merges dist-attr-annotated dense tensors
rank by rank. TPU-first the problem collapses to array placement: a
checkpoint leaf is a *global* array; converting it to a new mesh/spec is a
host gather (the loader already returns host arrays) followed by one
``jax.device_put`` under the target ``NamedSharding`` — GSPMD needs no
per-rank slicing logic because the sharded layout is derived from the spec
at placement time.

What this module adds over a bare ``device_put``:

- **Structure/shape/dtype validation first.** A checkpoint that cannot be
  converted (missing leaf, extra leaf, shape or dtype drift — e.g. a model
  whose config changed between save and resume) raises a structured
  :class:`CheckpointConversionError` naming the first mismatched leaf,
  instead of an opaque XLA error deep inside ``device_put``.
- **Accounting.** Conversions are counted (``converter.reshards``,
  ``converter.bytes``) and logged (``reshard`` run-log events with leaf
  count, bytes and seconds) so an elastic resume's reshard cost is visible
  in ``observability report``.
- **CRC safety.** ``CheckpointManager._load_verified`` verifies the
  manifest checksums on the *host* bytes before conversion, so the
  round-trip mesh A -> save -> restore on mesh B -> save -> restore on
  mesh A is bitwise (the CRC is computed over gathered host bytes, which
  resharding does not change).

Used by ``CheckpointManager.restore_latest(target=..., shardings=...)``
(distributed/resilience.py) and the elastic re-plan path
(``run_resilient`` + ``planner.elastic_replan``).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = ["CheckpointConversionError", "convert", "tree_shardings",
           "gather_to_host"]


class CheckpointConversionError(RuntimeError):
    """A checkpoint cannot be converted onto the requested target: the
    pytrees disagree (missing/extra leaf) or a leaf's shape/dtype changed.
    Carries ``.leaf`` — the tree path of the first mismatch."""

    def __init__(self, message: str, leaf: Optional[str] = None):
        super().__init__(message)
        self.leaf = leaf


def _flat(tree) -> Dict[str, Any]:
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(tree)
    return {keystr(path): leaf for path, leaf in leaves}


def tree_shardings(tree) -> Dict[str, Any]:
    """Tree path -> the leaf's sharding, for leaves that carry one (jax
    Arrays); host arrays map to None. The inverse question of ``convert``:
    what placement does this (target) state already have?"""
    out = {}
    for key, leaf in _flat(tree).items():  # noqa: PTA102 (host-side, never traced)
        out[key] = getattr(leaf, "sharding", None)  # noqa: PTA104 (host-side, never traced)
    return out


def gather_to_host(tree):
    """Every leaf as a host numpy array (full global value, any source
    sharding collapsed) — the first half of a cross-mesh conversion."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), tree)


def _leaf_sig(leaf):
    shape = tuple(getattr(leaf, "shape", ()) or ())
    try:
        dtype = str(np.dtype(leaf.dtype))
    except (TypeError, AttributeError):
        # extended dtypes (typed PRNG keys) have no numpy spelling; compare
        # their jax repr instead
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    return shape, dtype


def convert(state: Any, target: Optional[Any] = None,
            shardings: Optional[Any] = None, label: str = "checkpoint") -> Any:
    """Convert ``state`` (a loaded checkpoint pytree, host or device) onto
    a new placement.

    ``target`` gives the expected structure/shapes/dtypes (typically the
    freshly built state for the *new* topology); ``shardings`` is a
    matching pytree of ``NamedSharding`` for the new mesh. When
    ``shardings`` is None, each target leaf's own ``.sharding`` is used
    (so converting onto an already-placed state template "just works");
    leaves with no sharding anywhere stay host arrays.

    Validation happens before any placement: a structure or shape/dtype
    mismatch raises :class:`CheckpointConversionError` naming the first
    offending leaf. Returns the converted pytree with ``target``'s
    structure.
    """
    import time as _time

    import jax

    from ..observability import runlog as _runlog
    from ..observability.metrics import counter_inc as _counter_inc

    flat_state = _flat(state)
    if target is None:
        flat_target = flat_state
        structure_source = state
    else:
        flat_target = _flat(target)
        structure_source = target
        missing = sorted(set(flat_target) - set(flat_state))
        if missing:
            raise CheckpointConversionError(
                f"{label}: cannot convert — target expects leaf "
                f"{missing[0]!r} which the checkpoint does not contain "
                f"({len(missing)} missing leaf/leaves total)", leaf=missing[0])
        extra = sorted(set(flat_state) - set(flat_target))
        if extra:
            raise CheckpointConversionError(
                f"{label}: cannot convert — checkpoint contains leaf "
                f"{extra[0]!r} which the target does not expect "
                f"({len(extra)} extra leaf/leaves total)", leaf=extra[0])
        for key in sorted(flat_target):
            want, got = _leaf_sig(flat_target[key]), _leaf_sig(flat_state[key])
            if want != got:
                raise CheckpointConversionError(
                    f"{label}: cannot convert leaf {key!r} — checkpoint has "
                    f"{got[1]}{list(got[0])}, target expects "
                    f"{want[1]}{list(want[0])}; resharding changes placement, "
                    "never shapes/dtypes (did the model config change?)",
                    leaf=key)
    flat_shardings = _flat(shardings) if shardings is not None else {}

    t0 = _time.perf_counter()
    placed_bytes = 0
    placed_leaves = 0
    out = {}
    for key in flat_target:
        leaf = flat_state[key]
        sh = flat_shardings.get(key)
        if sh is None and target is not None:
            sh = getattr(flat_target[key], "sharding", None)
        if sh is None:
            out[key] = leaf  # noqa: PTA104 (host-side, never traced)
            continue
        # host gather -> re-place: one device_put under the new
        # NamedSharding does the slicing the reference converter hand-rolls
        host = np.asarray(jax.device_get(leaf))
        out[key] = jax.device_put(host, sh)  # noqa: PTA104 (host-side, never traced)
        placed_leaves += 1
        placed_bytes += host.nbytes
    seconds = _time.perf_counter() - t0

    # rebuild the target's tree structure from the flat dict
    from jax.tree_util import keystr, tree_flatten_with_path, tree_unflatten

    paths_leaves, treedef = tree_flatten_with_path(structure_source)
    converted = tree_unflatten(treedef, [out[keystr(p)] for p, _ in paths_leaves])
    if placed_leaves:
        _counter_inc("converter.reshards")
        _counter_inc("converter.bytes", placed_bytes)
        _runlog.emit("reshard", label=label, leaves=placed_leaves,
                     bytes=placed_bytes, seconds=seconds)
    return converted
