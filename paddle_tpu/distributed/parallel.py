"""DataParallel + spawn/launch parity (reference:
python/paddle/fluid/dygraph/parallel.py:419 DataParallel,
python/paddle/distributed/spawn.py, launch/main.py:18).

TPU-first: data parallelism is a mesh axis, not process replication. On a
single controller there is nothing to wrap — ``DataParallel`` exists for API
compat and simply scales the loss / passes through; the real DP path is
``fleet.distributed_step`` (grad all-reduce fused by XLA over 'dp').
Multi-host "launch" = one process per host with jax.distributed.initialize
(env.py), not one per device.
"""
from __future__ import annotations

from ..nn.layer.base import Layer
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env


def _cross_process_mean(value):
    """Eager all-reduce-mean across processes: one device per process forms a
    1-D mesh, the local value rides in as that process's shard, pmean inside
    shard_map produces the replicated mean (the eager analog of the
    reference Reducer's fused NCCL all-reduce, imperative/reducer.cc)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    first_local = {}
    for d in jax.devices():
        first_local.setdefault(d.process_index, d)
    mesh = Mesh(np.array([first_local[i] for i in range(jax.process_count())]), ("ddp",))
    sh = NamedSharding(mesh, P("ddp"))
    stacked = jax.make_array_from_process_local_data(sh, np.asarray(value)[None])
    out = jax.jit(
        jax.shard_map(lambda x: jax.lax.pmean(x, "ddp"), mesh=mesh, in_specs=P("ddp"), out_specs=P("ddp")),
        out_shardings=sh,
    )(stacked)
    return jnp.asarray(out.addressable_shards[0].data)[0]


class DataParallel(Layer):
    """Parity: python/paddle/fluid/dygraph/parallel.py:419.

    With ``world_size > 1`` (multi-host), every trainable parameter gets a
    grad hook that all-reduce-means its gradient across processes during
    ``loss.backward()`` — the reducer semantics (imperative/reducer.cc:127)
    without bucketing (XLA fuses the per-tensor reduces it can). Single
    process (one controller driving all local devices) needs no sync: there
    is exactly one copy of every parameter.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync = get_world_size() > 1
        self._sync_enabled = True
        self._hook_handles = []
        if self._grad_sync:
            for p in layers.parameters():
                if not p.stop_gradient:
                    self._hook_handles.append(p.register_hook(self._make_hook()))

    def _make_hook(self):
        def hook(grad):
            if not self._sync_enabled:
                return None
            from ..framework.core import _wrap_value

            return _wrap_value(_cross_process_mean(grad._value))

        return hook

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss  # hooks use pmean, so the loss needs no rescaling

    def apply_collective_grads(self):
        """Manual fallback (reference DataParallel.apply_collective_grads):
        all-reduce every .grad now — for use with no_sync() accumulation."""
        if not self._grad_sync:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                p.grad._value = _cross_process_mean(p.grad._value)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    @property
    def parameters(self):
        return self._layers.parameters

    def no_sync(self):
        """Skip grad sync inside the context (gradient accumulation)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev = self._sync_enabled
            self._sync_enabled = False
            try:
                yield
            finally:
                self._sync_enabled = prev

        return ctx()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Parity: paddle.distributed.spawn. Single-controller JAX drives all
    local devices from one process, so spawn degenerates to a direct call."""
    func(*args)


def launch():
    """Parity: python -m paddle.distributed.launch. On TPU pods, launch one
    process per host externally; init happens in env.init_parallel_env."""
    init_parallel_env()
