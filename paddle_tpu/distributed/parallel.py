"""DataParallel + spawn/launch parity (reference:
python/paddle/fluid/dygraph/parallel.py:419 DataParallel,
python/paddle/distributed/spawn.py, launch/main.py:18).

TPU-first: data parallelism is a mesh axis, not process replication. On a
single controller there is nothing to wrap — ``DataParallel`` exists for API
compat and simply scales the loss / passes through; the real DP path is
``fleet.distributed_step`` (grad all-reduce fused by XLA over 'dp').
Multi-host "launch" = one process per host with jax.distributed.initialize
(env.py), not one per device.
"""
from __future__ import annotations

from ..nn.layer.base import Layer
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    @property
    def parameters(self):
        return self._layers.parameters

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Parity: paddle.distributed.spawn. Single-controller JAX drives all
    local devices from one process, so spawn degenerates to a direct call."""
    func(*args)


def launch():
    """Parity: python -m paddle.distributed.launch. On TPU pods, launch one
    process per host externally; init happens in env.init_parallel_env."""
    init_parallel_env()
