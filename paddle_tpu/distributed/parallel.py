"""DataParallel + spawn/launch parity (reference:
python/paddle/fluid/dygraph/parallel.py:419 DataParallel,
python/paddle/distributed/spawn.py, launch/main.py:18).

TPU-first: data parallelism is a mesh axis, not process replication. On a
single controller there is nothing to wrap — ``DataParallel`` exists for API
compat and simply scales the loss / passes through; the real DP path is
``fleet.distributed_step`` (grad all-reduce fused by XLA over 'dp').
Multi-host "launch" = one process per host with jax.distributed.initialize
(env.py), not one per device.

Multi-host eager DDP is the reference Reducer redesigned for XLA
(imperative/reducer.cc:127): gradients are coalesced into ≤comm_buffer_size
MB flat buckets per dtype (bucket plan fixed at construction, so bucket
shapes — and therefore compiled collectives — are stable), each bucket is
all-reduce-meaned by ONE jitted shard_map over a process mesh built once in
``__init__``, and the flush runs from an end-of-backward callback rather
than per-parameter hooks (no per-grad dispatch, ≤ a couple of compiled
functions total).
"""
from __future__ import annotations

from ..nn.layer.base import Layer
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env


def _process_mesh():
    """1-D mesh with one (first) device per process."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    first_local = {}
    for d in jax.devices():
        first_local.setdefault(d.process_index, d)
    return Mesh(np.array([first_local[i] for i in range(jax.process_count())]), ("ddp",))


class _BucketReducer:
    """Coalesced cross-process grad averaging over a fixed bucket plan."""

    def __init__(self, params, comm_buffer_mb=25):
        import numpy as np

        self.mesh = _process_mesh()
        self._pmean = {}  # (n_elems, dtype) -> jitted shard_map
        # fixed bucket plan: group by dtype, fill to the byte budget
        budget = int(comm_buffer_mb * 1024 * 1024)
        by_dtype = {}
        for p in params:
            by_dtype.setdefault(str(p._value.dtype), []).append(p)
        self.buckets = []  # list of (dtype, [params])
        for dt, ps in by_dtype.items():
            cur, cur_bytes = [], 0
            for p in ps:
                nbytes = int(np.prod(p._value.shape or (1,))) * p._value.dtype.itemsize
                if cur and cur_bytes + nbytes > budget:
                    self.buckets.append((dt, cur))
                    cur, cur_bytes = [], 0
                cur.append(p)
                cur_bytes += nbytes
            if cur:
                self.buckets.append((dt, cur))

    def _pmean_fn(self, n, dtype):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (n, dtype)
        if key not in self._pmean:
            sh = NamedSharding(self.mesh, P("ddp"))
            self._pmean[key] = (
                jax.jit(
                    jax.shard_map(lambda x: jax.lax.pmean(x, "ddp"),
                                  mesh=self.mesh, in_specs=P("ddp"), out_specs=P("ddp")),
                    out_shardings=sh,
                ),
                sh,
            )
        return self._pmean[key]

    def reduce(self, find_unused_parameters=False):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..framework.core import _wrap_value

        all_ps = [p for _, ps in self.buckets for p in ps]
        have = [p for p in all_ps if p.grad is not None]
        if not have:
            return
        if not find_unused_parameters and len(have) < len(all_ps):
            missing = [p.name or "<unnamed>" for p in all_ps if p.grad is None]
            raise RuntimeError(
                "DataParallel: these parameters produced no gradient: "
                f"{missing}. Pass find_unused_parameters=True if parts of "
                "the model are intentionally unused (reference reducer "
                "semantics).")
        from .resilience import watchdog

        for bi, (dt, ps) in enumerate(self.buckets):
            grads = [p.grad for p in ps]
            if not any(g is not None for g in grads):
                continue  # whole bucket untouched this pass
            flat = jnp.concatenate([
                jnp.zeros(int(np.prod(p._value.shape or (1,))), p._value.dtype) if g is None
                else jnp.asarray(g._value).reshape(-1)
                for p, g in zip(ps, grads)
            ])
            fn, sh = self._pmean_fn(int(flat.shape[0]), dt)
            stacked = jax.make_array_from_process_local_data(sh, np.asarray(flat)[None])
            # a dead peer turns this collective into a silent infinite hang;
            # the watchdog (armed via FLAGS_collective_timeout_s) names the
            # bucket so the elastic layer's restart is attributable
            with watchdog(f"ddp all-reduce bucket {bi} ({dt}, "
                          f"{int(flat.shape[0])} elems)"):
                out = jnp.asarray(fn(stacked).addressable_shards[0].data)[0]
            off = 0
            for p, g in zip(ps, grads):
                n = int(np.prod(p._value.shape or (1,)))
                if g is not None:
                    g._value = out[off:off + n].reshape(p._value.shape)
                off += n


class DataParallel(Layer):
    """Parity: python/paddle/fluid/dygraph/parallel.py:419.

    With ``world_size > 1`` (multi-host), gradients are averaged across
    processes at the end of ``loss.backward()`` via the bucketed reducer
    above. Single process (one controller driving all local devices) needs
    no sync: there is exactly one copy of every parameter.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync = get_world_size() > 1
        self._sync_enabled = True
        self._hook_handles = []
        if self._grad_sync:
            import weakref

            from ..framework.autograd import register_post_backward_callback

            tracked = [p for p in layers.parameters() if not p.stop_gradient]
            self._reducer = _BucketReducer(tracked, comm_buffer_mb=comm_buffer_size)
            # grad hooks mark which params participated in THIS backward pass
            # (persisted grads from prior passes are already process-identical
            # after their own sync; re-averaging them is the identity, so the
            # pending set only gates cost/which-model, not correctness)
            self._pending = set()
            for p in tracked:
                pid = id(p)
                self._hook_handles.append(
                    p.register_hook(lambda g, _pid=pid, _s=self: _s._pending.add(_pid) or None))

            ref = weakref.ref(self)
            handle_cell = []

            def flush():
                dp = ref()
                if dp is None:  # wrapper discarded: self-deregister
                    if handle_cell:
                        handle_cell[0].remove()
                    return
                if not dp._sync_enabled or not dp._pending:
                    return
                dp._pending.clear()
                dp._reducer.reduce(dp.find_unused_parameters)

            handle_cell.append(register_post_backward_callback(flush))
            self._hook_handles.append(handle_cell[0])

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss  # reducer uses pmean, so the loss needs no rescaling

    def apply_collective_grads(self):
        """Manual fallback (reference DataParallel.apply_collective_grads):
        all-reduce every .grad now — for use with no_sync() accumulation."""
        if not self._grad_sync:
            return
        self._reducer.reduce(find_unused_parameters=True)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    @property
    def parameters(self):
        return self._layers.parameters

    def no_sync(self):
        """Skip grad sync inside the context (gradient accumulation)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev = self._sync_enabled
            self._sync_enabled = False
            try:
                yield
            finally:
                self._sync_enabled = prev

        return ctx()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Parity: paddle.distributed.spawn (python/paddle/distributed/spawn.py).

    On TPU the canonical layout is one process per HOST driving all local
    chips (single controller), so ``nprocs=-1`` or 1 is a direct call. An
    explicit ``nprocs > 1`` genuinely forks: each worker is a spawned
    process with the PADDLE_* rendezvous env (master port from
    ``options['master']`` or an ephemeral one) — the multi-host path used
    by the eager DataParallel tests, for CPU-backed multi-process runs.
    Returns the context object with ``.join()`` like the reference.
    """
    if nprocs in (-1, 0, 1):
        func(*args)
        return None

    import multiprocessing as mp
    import socket

    master = options.get("master")
    if master is None:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()

    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_spawn_worker, args=(func, args, rank, nprocs, master),
                        daemon=daemon)
        p.start()
        procs.append(p)

    class _Ctx:
        processes = procs

        @staticmethod
        def join(timeout=None):
            rc = 0
            for p in procs:
                p.join(timeout)
                if p.exitcode:
                    rc = p.exitcode
            if rc:
                raise RuntimeError(f"spawn: a worker exited with code {rc}")
            return True

    if join:
        _Ctx.join()
        return None
    return _Ctx()


def _spawn_worker(func, args, rank, world, master):
    import os

    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_MASTER": master,
        "PADDLE_LOCAL_RANK": str(rank),
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
    })
    func(*args)


def launch():
    """Parity: python -m paddle.distributed.launch. On TPU pods, launch one
    process per host externally; init happens in env.init_parallel_env."""
    init_parallel_env()
