"""TCPStore: key-value rendezvous for multi-host bootstrap.

Parity: ``paddle.distributed.TCPStore`` (reference
paddle/fluid/distributed/store/tcp_store.h:97 + pybind). The store itself is
native C++ (csrc/tcp_store.cc); this wraps it with the reference's Python API
(set/get/add/wait) plus a ``barrier``. On TPU pods the heavy collectives ride
XLA over ICI/DCN — the store only exchanges small bootstrap blobs (coordinator
address, per-host metadata), exactly the role the reference's store plays for
NCCL comm-id exchange.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

from ..framework import native
from ..testing import chaos


class BarrierTimeoutError(TimeoutError):
    """A diagnostic barrier expired; names exactly which ranks never
    arrived (``missing_ranks``) instead of a bare TimeoutError."""

    def __init__(self, name: str, missing_ranks: List[int], arrived: List[int],
                 timeout: float):
        self.name = name
        self.missing_ranks = list(missing_ranks)
        self.arrived = list(arrived)
        super().__init__(
            f"barrier {name!r} timed out after {timeout:g}s: "
            f"rank(s) {self.missing_ranks} never arrived "
            f"(arrived: {self.arrived})")


class TCPStore:
    """Client handle to a TCP key-value store; rank 0 also hosts the server.

    Args mirror the reference binding: ``host``, ``port``, ``is_master``
    (start the in-process server), ``world_size``, ``timeout`` (seconds).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self._lib = native.load_native()
        self._server = None
        self.world_size = world_size
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = self._lib.pt_store_server_start(port)
            if not self._server:
                raise OSError(f"TCPStore: cannot bind server on port {port}")
            port = self._lib.pt_store_server_port(self._server)
        self.host, self.port = host, port
        self._client = self._lib.pt_store_client_create(host.encode(), port, self.timeout_ms)
        if not self._client:
            self._shutdown_server()
            raise ConnectionError(f"TCPStore: cannot connect to {host}:{port}")

    # ------------------------------------------------------------- basic ops
    def set(self, key: str, value) -> None:
        chaos.store_op("set", key)
        data = value.encode() if isinstance(value, str) else bytes(value)
        if self._lib.pt_store_set(self._client, key.encode(), data, len(data)) != 0:
            raise OSError(f"TCPStore.set({key!r}) failed")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        """Blocks until the key exists (reference Store::get semantics)."""
        import ctypes

        chaos.store_op("get", key)
        out = ctypes.c_void_p()
        tmo = self.timeout_ms if timeout is None else int(timeout * 1000)
        n = self._lib.pt_store_get(self._client, key.encode(), ctypes.byref(out), tmo)
        if n < 0:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out after {tmo} ms")
        data = ctypes.string_at(out, n)
        self._lib.pt_buffer_free(out)
        return data

    def add(self, key: str, amount: int = 1) -> int:
        chaos.store_op("add", key)
        r = self._lib.pt_store_add(self._client, key.encode(), amount)
        if r == -(2**63):
            raise OSError(f"TCPStore.add({key!r}) failed")
        return r

    def delete_key(self, key: str) -> bool:
        r = self._lib.pt_store_del(self._client, key.encode())
        if r < 0:
            raise OSError(f"TCPStore.delete_key({key!r}) failed")
        return r == 1

    def num_keys(self) -> int:
        n = self._lib.pt_store_num_keys(self._client)
        if n < 0:
            raise OSError("TCPStore.num_keys failed")
        return n

    def wait(self, keys: List[str], timeout: Optional[float] = None) -> None:
        for k in keys:
            self.get(k, timeout=timeout)

    # ------------------------------------------------------------ rendezvous
    def barrier(self, name: str = "default", timeout: Optional[float] = None) -> None:
        """All ``world_size`` participants block until everyone arrives."""
        arrived = self.add(f"__barrier__/{name}/count", 1)
        round_ = (arrived - 1) // self.world_size  # store survives reuse
        target = (round_ + 1) * self.world_size
        if arrived == target:
            self.set(f"__barrier__/{name}/release/{round_}", b"1")
        self.get(f"__barrier__/{name}/release/{round_}", timeout=timeout)

    def diagnostic_barrier(self, rank: int, name: str = "default",
                           timeout: Optional[float] = None,
                           poll: float = 0.05) -> None:
        """Barrier with per-rank arrival keys: a timeout raises
        BarrierTimeoutError naming exactly the ranks that never showed up
        (vs ``barrier``'s counter, which can only say "someone").

        Arrival keys persist in the store, so reuse needs a fresh ``name``
        per synchronization point (e.g. suffix the step number).
        """
        self.set(f"__dbarrier__/{name}/arrived/{rank}", b"1")
        tmo = self.timeout_ms / 1000.0 if timeout is None else timeout
        deadline = time.monotonic() + tmo
        missing = set(range(self.world_size))
        while missing:
            for r in sorted(missing):
                try:
                    self.get(f"__dbarrier__/{name}/arrived/{r}", timeout=poll)
                    missing.discard(r)
                except TimeoutError:
                    pass
            if missing and time.monotonic() >= deadline:
                arrived = sorted(set(range(self.world_size)) - missing)
                raise BarrierTimeoutError(name, sorted(missing), arrived, tmo)

    def _shutdown_server(self):
        if self._server:
            self._lib.pt_store_server_stop(self._server)
            self._server = None

    def close(self):
        if getattr(self, "_client", None):
            self._lib.pt_store_client_destroy(self._client)
            self._client = None
        self._shutdown_server()

    def __del__(self):
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown
            pass


def rendezvous_store(world_size: int, rank: int,
                     endpoint: Optional[str] = None) -> TCPStore:
    """Build the bootstrap store from env, reference parallel.py:267 style.

    Rank 0 hosts; everyone connects. ``endpoint`` or ``PADDLE_MASTER``
    formatted ``host:port``.
    """
    ep = endpoint or os.environ.get("PADDLE_MASTER", "127.0.0.1:34219")
    host, port = ep.rsplit(":", 1)
    return TCPStore(host, int(port), is_master=(rank == 0), world_size=world_size)
