"""TCPStore: key-value rendezvous for multi-host bootstrap.

Parity: ``paddle.distributed.TCPStore`` (reference
paddle/fluid/distributed/store/tcp_store.h:97 + pybind). The store itself is
native C++ (csrc/tcp_store.cc); this wraps it with the reference's Python API
(set/get/add/wait) plus a ``barrier``. On TPU pods the heavy collectives ride
XLA over ICI/DCN — the store only exchanges small bootstrap blobs (coordinator
address, per-host metadata), exactly the role the reference's store plays for
NCCL comm-id exchange.
"""
from __future__ import annotations

import os
from typing import List, Optional

from ..framework import native


class TCPStore:
    """Client handle to a TCP key-value store; rank 0 also hosts the server.

    Args mirror the reference binding: ``host``, ``port``, ``is_master``
    (start the in-process server), ``world_size``, ``timeout`` (seconds).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self._lib = native.load_native()
        self._server = None
        self.world_size = world_size
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = self._lib.pt_store_server_start(port)
            if not self._server:
                raise OSError(f"TCPStore: cannot bind server on port {port}")
            port = self._lib.pt_store_server_port(self._server)
        self.host, self.port = host, port
        self._client = self._lib.pt_store_client_create(host.encode(), port, self.timeout_ms)
        if not self._client:
            self._shutdown_server()
            raise ConnectionError(f"TCPStore: cannot connect to {host}:{port}")

    # ------------------------------------------------------------- basic ops
    def set(self, key: str, value) -> None:
        data = value.encode() if isinstance(value, str) else bytes(value)
        if self._lib.pt_store_set(self._client, key.encode(), data, len(data)) != 0:
            raise OSError(f"TCPStore.set({key!r}) failed")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        """Blocks until the key exists (reference Store::get semantics)."""
        import ctypes

        out = ctypes.c_void_p()
        tmo = self.timeout_ms if timeout is None else int(timeout * 1000)
        n = self._lib.pt_store_get(self._client, key.encode(), ctypes.byref(out), tmo)
        if n < 0:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out after {tmo} ms")
        data = ctypes.string_at(out, n)
        self._lib.pt_buffer_free(out)
        return data

    def add(self, key: str, amount: int = 1) -> int:
        r = self._lib.pt_store_add(self._client, key.encode(), amount)
        if r == -(2**63):
            raise OSError(f"TCPStore.add({key!r}) failed")
        return r

    def delete_key(self, key: str) -> bool:
        r = self._lib.pt_store_del(self._client, key.encode())
        if r < 0:
            raise OSError(f"TCPStore.delete_key({key!r}) failed")
        return r == 1

    def num_keys(self) -> int:
        n = self._lib.pt_store_num_keys(self._client)
        if n < 0:
            raise OSError("TCPStore.num_keys failed")
        return n

    def wait(self, keys: List[str], timeout: Optional[float] = None) -> None:
        for k in keys:
            self.get(k, timeout=timeout)

    # ------------------------------------------------------------ rendezvous
    def barrier(self, name: str = "default", timeout: Optional[float] = None) -> None:
        """All ``world_size`` participants block until everyone arrives."""
        arrived = self.add(f"__barrier__/{name}/count", 1)
        round_ = (arrived - 1) // self.world_size  # store survives reuse
        target = (round_ + 1) * self.world_size
        if arrived == target:
            self.set(f"__barrier__/{name}/release/{round_}", b"1")
        self.get(f"__barrier__/{name}/release/{round_}", timeout=timeout)

    def _shutdown_server(self):
        if self._server:
            self._lib.pt_store_server_stop(self._server)
            self._server = None

    def close(self):
        if getattr(self, "_client", None):
            self._lib.pt_store_client_destroy(self._client)
            self._client = None
        self._shutdown_server()

    def __del__(self):
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown
            pass


def rendezvous_store(world_size: int, rank: int,
                     endpoint: Optional[str] = None) -> TCPStore:
    """Build the bootstrap store from env, reference parallel.py:267 style.

    Rank 0 hosts; everyone connects. ``endpoint`` or ``PADDLE_MASTER``
    formatted ``host:port``.
    """
    ep = endpoint or os.environ.get("PADDLE_MASTER", "127.0.0.1:34219")
    host, port = ep.rsplit(":", 1)
    return TCPStore(host, int(port), is_master=(rank == 0), world_size=world_size)
