"""Pipeline parallelism (parity: fleet/meta_parallel/pipeline_parallel.py:31
— PipelineLayer pp_layers.py:162 + 1F1B train_batch:154 + p2p helpers
pp_utils/p2p_communication.py:222).

TPU-first design: the pipeline is a *single SPMD program*. Stage weights are
stacked on a leading axis sharded over the 'pp' mesh axis; microbatch
activations move between stages with ``lax.ppermute`` (the collective-permute
analog of send_v2/recv_v2) inside a ``lax.fori_loop`` schedule. Autodiff
through ppermute gives the backward pipeline for free (its transpose is the
reverse permute), so fwd+bwd is one XLA computation — no host-driven 1F1B
interleave, no interceptor runtime (fleet_executor/). The shard_map is
*partial-manual* (``axis_names={'pp'}``): only the pipeline axis is manual,
so dp/sdp batch sharding and mp tensor parallelism inside each stage remain
GSPMD-automatic and compose with the pipeline. Memory behaves like GPipe;
combine with remat (per-layer jax.checkpoint) for 1F1B-like footprints.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def spmd_pipeline(stage_fn: Callable, stacked_params: Any, x_mb: jnp.ndarray, mesh: Mesh, axis: str = "pp", remat: bool = False, extras: Tuple = (), mb_index: bool = False, schedule: str = "gpipe"):
    """Run ``stage_fn`` as an ``n_stages``-deep pipeline over microbatches.

    stage_fn(layer_params, x, *extras) -> y applies ONE layer; y.shape == x.shape.
    stacked_params: pytree; every leaf has leading dim L (the total layer
        count), a multiple of ``n_stages``. Stage ``s`` holds layers
        [s*L/n, (s+1)*L/n) and scans ``stage_fn`` over them.
    x_mb: [n_micro, micro_batch, ...] microbatched input (replicated over
        ``axis``; dp/mp sharding of the trailing dims stays automatic).
    extras: arrays passed through to every stage_fn call (e.g. dropout keys).
    mb_index: if True, stage_fn is called as
        stage_fn(layer_params, x, mb_idx, *extras) with the scalar microbatch
        index being processed — needed e.g. to draw distinct dropout masks
        per microbatch.
    schedule: the *memory* schedule (reference pipeline_parallel.py:154
        startup/steady/cooldown 1F1B). In a single-SPMD-program pipeline the
        XLA scheduler owns op ordering, so the honest analog of 1F1B is its
        memory bound: ``"1f1b"`` rematerializes every stage application, so
        only the O(n_micro) stage-BOUNDARY activations are stored and the
        per-layer residual footprint is O(1) microbatches — at or below the
        reference 1F1B's O(pp) in-flight activations (measured: test_pipeline
        ``test_1f1b_memory_bound`` via compiled.memory_analysis()).
        ``"gpipe"`` keeps all residuals (fastest when memory allows).
    returns [n_micro, micro_batch, ...] outputs of the final stage.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"pipeline schedule must be 'gpipe' or '1f1b', got {schedule!r}")
    n_stages = mesh.shape[axis]
    n_micro = x_mb.shape[0]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    n_layers = leaves[0].shape[0]
    assert n_layers % n_stages == 0, f"{n_layers} layers not divisible by {n_stages} stages"
    body = jax.checkpoint(stage_fn) if (remat or schedule == "1f1b") else stage_fn

    def apply_stage(params_local, h, mb, extra):
        def scan_body(hh, lp):
            if mb_index:
                return body(lp, hh, mb, *extra), None
            return body(lp, hh, *extra), None

        h, _ = jax.lax.scan(scan_body, h, params_local)
        return h

    def per_stage(params_local, x, *extra):
        stage_id = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        # carries are per-stage values: mark them device-varying over 'pp'
        state = jax.lax.pcast(jnp.zeros_like(x[0]), (axis,), to="varying")
        outputs = jax.lax.pcast(jnp.zeros_like(x), (axis,), to="varying")

        def tick(t, carry):
            state, outputs = carry
            mb_in = jax.lax.dynamic_index_in_dim(x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(stage_id == 0, mb_in, state)
            # the microbatch flowing through stage s at tick t entered at
            # tick t-s: that index keys per-microbatch randomness
            mb = jnp.clip(t - stage_id, 0, n_micro - 1)
            out = apply_stage(params_local, inp, mb, extra)
            out_t = t - (n_stages - 1)
            write = (stage_id == n_stages - 1) & (out_t >= 0)
            upd = jax.lax.dynamic_update_index_in_dim(outputs, out, jnp.clip(out_t, 0, n_micro - 1), axis=0)
            outputs = jnp.where(write, upd, outputs)
            state = jax.lax.ppermute(out, axis, perm)
            return state, outputs

        state, outputs = jax.lax.fori_loop(0, n_micro + n_stages - 1, tick, (state, outputs))
        # broadcast the last stage's outputs across the pp axis
        src = n_stages - 1
        outputs = jax.lax.psum(jnp.where(jax.lax.axis_index(axis) == src, outputs, jnp.zeros_like(outputs)), axis)
        return outputs

    param_specs = jax.tree_util.tree_map(lambda p: P(axis), stacked_params)
    mapped = jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, P()) + tuple(P() for _ in extras),
        out_specs=P(),
        axis_names={axis},
    )
    return mapped(stacked_params, x_mb, *extras)


def microbatch(x: jnp.ndarray, n_micro: int, mesh: Optional[Mesh] = None):
    """[B, ...] -> [n_micro, B/n_micro, ...] with microbatch i = rows i::n_micro.

    The strided assignment keeps each device's dp-shard of the batch local:
    reshape [B] -> [B/n_micro, n_micro] splits within each device's contiguous
    block, so no cross-device resharding (the contiguous-chunk reshape would
    reassign rows across the dp axis).
    """
    b = x.shape[0]
    assert b % n_micro == 0, f"batch {b} not divisible by {n_micro} microbatches"
    xm = jnp.swapaxes(x.reshape(b // n_micro, n_micro, *x.shape[1:]), 0, 1)
    if mesh is not None:
        xm = jax.lax.with_sharding_constraint(xm, NamedSharding(mesh, P(None, ("dp", "sdp"))))
    return xm


def unmicrobatch(xm: jnp.ndarray, mesh: Optional[Mesh] = None):
    """Inverse of :func:`microbatch`."""
    n_micro, mb = xm.shape[0], xm.shape[1]
    x = jnp.swapaxes(xm, 0, 1).reshape(n_micro * mb, *xm.shape[2:])
    if mesh is not None:
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(("dp", "sdp"))))
    return x


def active_pipeline_plan():
    """(mesh, n_micro) for the live fleet pipeline, or (None, 1).

    Consumes ``strategy.pipeline_configs.accumulate_steps`` (parity:
    distributed_strategy.proto pipeline micro_batch config) — the piece
    fleet.distributed_step routes into the model trunk.
    """
    from .fleet import fleet

    if fleet._hcg is None:
        return None, 1
    mesh = fleet._hcg.mesh
    pp = mesh.shape.get("pp", 1)
    if pp <= 1:
        return None, 1
    n_micro = 1
    if fleet._strategy is not None:
        n_micro = int(fleet._strategy.pipeline_configs.accumulate_steps)
    if n_micro <= 1:
        n_micro = 2 * pp  # default: enough microbatches to keep bubbles ~1/3
    return mesh, n_micro


def active_pipeline_schedule() -> str:
    """The live strategy's pipeline memory schedule ('gpipe' | '1f1b')."""
    from .fleet import fleet

    if fleet._strategy is not None:
        return fleet._strategy.pipeline_configs.schedule
    return "gpipe"


class LayerDesc:
    """Parity: pp_layers.py:58."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Parity: pp_layers.py:77 (shared embeddings across stages). Under a
    single controller sharing is free: both references resolve to the same
    Parameter object."""

    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Parity: pp_layers.py:92 — split a LayerDesc list into pp_degree
    segments, balancing layer count."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        base = n // self.num_parts
        extra = n % self.num_parts
        bounds = [0]
        for i in range(self.num_parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds


class PipelineLayer:
    """Parity: PipelineLayer (pp_layers.py:162).

    TPU-first execution: when the built layers form a *homogeneous* run (same
    class, identical parameter shapes — the GPT/BERT trunk pattern) and a
    fleet mesh with pp>1 is live, their parameters are stacked on a leading
    axis and the run executes through :func:`spmd_pipeline`, microbatched and
    genuinely pipelined over the 'pp' axis. Heterogeneous prefix/suffix
    layers (embedding, head) run replicated across stages — the analog of
    the reference's shared first/last-stage layers. Without a pp mesh the
    layers run sequentially (single-stage pipeline).
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None, seg_method="uniform", recompute_interval=0, **kwargs):
        self.descs = layers
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        self.segments = SegmentLayers(layers, num_stages or 1).do_segment()
        self.built = [d.build_layer() if isinstance(d, LayerDesc) else d for d in layers]
        self._homo = self._homogeneous_run()

    def _homogeneous_run(self):
        """Longest run [i, j) of built layers with identical class + param
        shape signature — the pipelinable trunk."""
        from ..nn.layer.base import Layer

        def sig(l):
            if not isinstance(l, Layer):
                return None
            shapes = tuple((n, tuple(p.shape)) for n, p in sorted(l.named_parameters()))
            return (type(l), shapes)

        sigs = [sig(l) for l in self.built]
        best = (0, 0)
        i = 0
        while i < len(sigs):
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < len(sigs) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = j
        return best

    def forward(self, x):
        mesh, n_micro = active_pipeline_plan()
        lo, hi = self._homo
        n_run = hi - lo
        pipelined = (
            mesh is not None
            and n_run >= 2
            and n_run % mesh.shape["pp"] == 0
        )
        if not pipelined:
            for layer in self.built:
                x = layer(x) if callable(layer) else x
            return x

        from ..framework.core import Tensor, unwrap
        from ..tensor._helpers import ensure_tensor, op

        for layer in self.built[:lo]:
            x = layer(x) if callable(layer) else x

        run = self.built[lo:hi]
        # stack homogeneous params: leaf k = stack of layer-i's k-th param
        names = [n for n, _ in sorted(run[0].named_parameters())]
        stacked_tensors = []
        for n in names:
            per_layer = [dict(sorted(l.named_parameters()))[n] for l in run]
            stacked_tensors.append(per_layer)
        proto = run[0]

        def fn(xx, *flat):
            import jax.numpy as jnp

            stacks = [jnp.stack(flat[i * n_run:(i + 1) * n_run]) for i in range(len(names))]

            def stage_fn(lp, h):
                arrays = dict(zip(names, lp))
                with proto.bind(arrays):
                    out = proto(ensure_tensor(h))
                return unwrap(out)

            xm = microbatch(xx, n_micro, mesh)
            out = spmd_pipeline(stage_fn, tuple(stacks), xm, mesh,
                                remat=self.recompute_interval > 0,
                                schedule=active_pipeline_schedule())
            return unmicrobatch(out, mesh)

        flat = [p for group in stacked_tensors for p in group]
        x = op(fn, ensure_tensor(x), *flat, _name="pipeline_layer")
        for layer in self.built[hi:]:
            x = layer(x) if callable(layer) else x
        return x

    __call__ = forward

    def parameters(self):
        out = []
        for l in self.built:
            if hasattr(l, "parameters"):
                out.extend(l.parameters())
        return out
