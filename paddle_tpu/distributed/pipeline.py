"""Pipeline parallelism (parity: fleet/meta_parallel/pipeline_parallel.py:31
— PipelineLayer pp_layers.py:162 + 1F1B train_batch:154 + p2p helpers
pp_utils/p2p_communication.py:222).

TPU-first design: the pipeline is a *single SPMD program*. Stage weights are
stacked on a leading axis sharded over the 'pp' mesh axis; microbatch
activations move between stages with ``lax.ppermute`` (the collective-permute
analog of send_v2/recv_v2) inside a ``lax.fori_loop`` schedule. Autodiff
through ppermute gives the backward pipeline for free (its transpose is the
reverse permute), so fwd+bwd is one XLA computation — no host-driven 1F1B
interleave, no interceptor runtime (fleet_executor/). Memory behaves like
GPipe; combine with remat (jax.checkpoint on stage_fn) for 1F1B-like
footprints.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def spmd_pipeline(stage_fn: Callable, stacked_params: Any, x_mb: jnp.ndarray, mesh: Mesh, axis: str = "pp", remat: bool = False):
    """Run ``stage_fn`` as an ``n_stages``-deep pipeline over microbatches.

    stage_fn(local_params, x) -> y with y.shape == x.shape
    stacked_params: pytree; every leaf has leading dim n_stages
    x_mb: [n_micro, micro_batch, ...] microbatched input (replicated)
    returns [n_micro, micro_batch, ...] outputs of the final stage (replicated)
    """
    n_stages = mesh.shape[axis]
    n_micro = x_mb.shape[0]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def per_stage(params_local, x):
        params_local = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), params_local)
        stage_id = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        state = jnp.zeros_like(x[0])
        outputs = jnp.zeros_like(x)

        def tick(t, carry):
            state, outputs = carry
            mb_in = jax.lax.dynamic_index_in_dim(x, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(stage_id == 0, mb_in, state)
            out = stage_fn(params_local, inp)
            out_t = t - (n_stages - 1)
            write = (stage_id == n_stages - 1) & (out_t >= 0)
            upd = jax.lax.dynamic_update_index_in_dim(outputs, out, jnp.clip(out_t, 0, n_micro - 1), axis=0)
            outputs = jnp.where(write, upd, outputs)
            state = jax.lax.ppermute(out, axis, perm)
            return state, outputs

        state, outputs = jax.lax.fori_loop(0, n_micro + n_stages - 1, tick, (state, outputs))
        # make outputs replicated across the pp axis (only last stage wrote)
        src = n_stages - 1
        outputs = jax.lax.psum(jnp.where(jax.lax.axis_index(axis) == src, outputs, jnp.zeros_like(outputs)), axis)
        return outputs

    param_specs = jax.tree_util.tree_map(lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    mapped = jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    return mapped(stacked_params, x_mb)


class LayerDesc:
    """Parity: pp_layers.py:58."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Parity: pp_layers.py:77 (shared embeddings across stages). Under a
    single controller sharing is free: both references resolve to the same
    Parameter object."""

    def __init__(self, key, layer_cls, *args, forward_func=None, shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Parity: pp_layers.py:92 — split a LayerDesc list into pp_degree
    segments, balancing layer count."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        base = n // self.num_parts
        extra = n % self.num_parts
        bounds = [0]
        for i in range(self.num_parts):
            bounds.append(bounds[-1] + base + (1 if i < extra else 0))
        return bounds


class PipelineLayer:
    """Parity: PipelineLayer (pp_layers.py:162). Holds the LayerDesc list and
    segment boundaries; the jit path consumes the stacked-parameter form via
    spmd_pipeline. Provided for API compat — the TPU-first way to write a
    pipelined model is a homogeneous stacked-block trunk (see
    models/gpt.py GPTModel, whose blocks already live on a stacked leading
    axis ready to shard over 'pp')."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None, seg_method="uniform", recompute_interval=0, **kwargs):
        self.descs = layers
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.segments = SegmentLayers(layers, num_stages or 1).do_segment()
        self.built = [d.build_layer() if isinstance(d, LayerDesc) else d for d in layers]

    def forward(self, x):
        for layer in self.built:
            x = layer(x) if callable(layer) else x
        return x

    __call__ = forward
