"""Activation recompute (parity: fleet/utils/recompute.py:209
RecomputeFunction / recompute():346 + static pass
distributed/passes/auto_parallel_recompute.py).

TPU-first: ``jax.checkpoint`` (remat) with selectable policies. The
reference replays RNG state for dropout inside the recomputed segment —
JAX keys are pure inputs, so replay is automatic.
"""
from __future__ import annotations

import jax

from ..framework.core import Tensor, unwrap
from ..nn.functional_api import _wrap_tree, unwrap_tree

POLICIES = {
    "none": None,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "dots_with_no_batch_dims_saveable": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def recompute(function, *args, policy="nothing_saveable", **kwargs):
    """Eager-compatible recompute: runs ``function`` (Tensor-level) under a
    remat boundary when traced; in pure eager it simply calls through (the
    tape already stores residuals per op, so eager recompute is a no-op —
    memory thrift comes on the jit path, matching how the reference's
    recompute only matters under large models)."""
    return function(*args, **kwargs)


def remat(fn, policy="nothing_saveable", prevent_cse=True, static_argnums=()):
    """Array-level remat wrapper for functional/jit code paths."""
    pol = POLICIES.get(policy, None) if isinstance(policy, str) else policy
    return jax.checkpoint(fn, policy=pol, prevent_cse=prevent_cse, static_argnums=static_argnums)
