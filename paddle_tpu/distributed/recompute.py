"""Activation recompute (parity: fleet/utils/recompute.py:209
RecomputeFunction / recompute():346 + static pass
distributed/passes/auto_parallel_recompute.py).

TPU-first: ``jax.checkpoint`` (remat) with selectable policies. The
reference replays RNG state for dropout inside the recomputed segment —
JAX keys are pure inputs, so replay is automatic.
"""
from __future__ import annotations

import jax

from ..framework.core import Tensor, unwrap
from ..nn.functional_api import _wrap_tree, unwrap_tree

POLICIES = {
    "none": None,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "dots_with_no_batch_dims_saveable": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def recompute(function, *args, policy="nothing_saveable", **kwargs):
    """Eager-compatible recompute: runs ``function`` (Tensor-level) under a
    ``jax.checkpoint`` boundary when any input is traced (i.e. under jit);
    in pure eager it simply calls through (the tape already stores residuals
    per op, so eager recompute is a no-op — memory thrift comes on the jit
    path, matching how the reference's recompute only matters under large
    models). Parameters the function closes over stay saveable constants of
    the remat segment — only activations are recomputed."""
    pol = _policy(policy)  # validate BEFORE the eager early-return so a
    vals = unwrap_tree(list(args))  # typo'd name fails on first call
    kwvals = unwrap_tree(dict(kwargs))

    def _traced(v):
        return any(isinstance(l, jax.core.Tracer) for l in jax.tree_util.tree_leaves(v))

    # only traced args cross the checkpoint boundary; everything else (bools,
    # ints, concrete arrays) rides the closure so functions that branch on
    # flag arguments keep working — mirrors how the reference recompute
    # accepts mixed tensor/non-tensor args (fleet/utils/recompute.py:346)
    dyn_i = [i for i, v in enumerate(vals) if _traced(v)]
    dyn_k = [k for k, v in kwvals.items() if _traced(v)]
    if not dyn_i and not dyn_k:
        return function(*args, **kwargs)

    def _arr_fn(dyn_args, dyn_kwargs):
        full = list(args)
        for i, v in zip(dyn_i, dyn_args):
            full[i] = _wrap_tree(v)
        kw = dict(kwargs)
        for k in dyn_k:
            kw[k] = _wrap_tree(dyn_kwargs[k])
        return unwrap_tree(function(*full, **kw))

    out = jax.checkpoint(_arr_fn, policy=pol)(
        [vals[i] for i in dyn_i], {k: kwvals[k] for k in dyn_k})
    return _wrap_tree(out)


def _policy(policy):
    """Resolve a policy name; unknown strings raise instead of silently
    degrading to full remat (a typo like 'dots_savable' would otherwise
    change memory/compute behavior with no error)."""
    if not isinstance(policy, str):
        return policy
    if policy not in POLICIES:
        raise ValueError(f"unknown recompute policy {policy!r}; "
                         f"expected one of {sorted(POLICIES)}")
    return POLICIES[policy]


def remat(fn, policy="nothing_saveable", prevent_cse=True, static_argnums=()):
    """Array-level remat wrapper for functional/jit code paths."""
    return jax.checkpoint(fn, policy=_policy(policy), prevent_cse=prevent_cse, static_argnums=static_argnums)
