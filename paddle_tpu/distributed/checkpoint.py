"""Distributed checkpointing with resharding-on-load (parity:
auto_parallel/dist_saver.py + converter.py; fleet.save_persistables
fleet/base/fleet_base.py:833).

TPU-first: orbax-checkpoint — async, per-shard parallel IO (tensorstore),
and restore onto a *different* mesh/sharding by passing target shardings
(the reference's converter.py reshard-on-load)."""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def checksum_pytree(state: Any) -> dict:
    """Per-leaf content checksums: tree path -> {crc32, shape, dtype}.

    The CheckpointManager (distributed/resilience.py) stores this in each
    checkpoint's manifest and re-computes it on restore, so a truncated or
    bit-flipped checkpoint is detected instead of silently resuming from
    garbage. CRC32 over the host bytes: integrity against torn writes, not
    an adversary."""
    import zlib

    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(state)
    out = {}
    for path, leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        out[keystr(path)] = {
            "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
    return out


def save_state(state: Any, path: str, async_save: bool = False):
    """Save a (possibly sharded) pytree state. Returns when durable unless
    async_save (then returns a handle with .wait())."""
    ocp = _ocp()
    path = os.path.abspath(path)
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler()) if async_save else ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(path, state, force=True)
    return ckptr


def load_state(path: str, target: Optional[Any] = None, shardings: Optional[Any] = None):
    """Restore. ``target`` gives dtypes/shapes; ``shardings`` (pytree of
    NamedSharding) reshards onto the current mesh — may differ from the mesh
    the checkpoint was written with."""
    ocp = _ocp()
    path = os.path.abspath(path)
    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    if target is None:
        return ckptr.restore(path)
    if shardings is not None:
        abstract = jax.tree_util.tree_map(
            lambda arr, sh: jax.ShapeDtypeStruct(np.shape(arr), arr.dtype, sharding=sh),
            target,
            shardings,
        )
    else:
        abstract = jax.tree_util.tree_map(lambda arr: jax.ShapeDtypeStruct(np.shape(arr), arr.dtype), target)
    restore_args = jax.tree_util.tree_map(
        lambda a: ocp.ArrayRestoreArgs(sharding=a.sharding) if getattr(a, "sharding", None) is not None else ocp.RestoreArgs(),
        abstract,
    )
    return ckptr.restore(path, restore_args=restore_args)


def save_train_step(train_step, path: str, async_save: bool = False):
    """Checkpoint a jit.TrainStep's full state (params+opt+buffers+step).
    PRNG keys are stored as raw key data (typed keys aren't serializable)."""
    state = dict(train_step.state)
    state["rng"] = jax.random.key_data(state["rng"])
    return save_state(state, path, async_save=async_save)


def load_train_step(train_step, path: str, shardings: Optional[Any] = None):
    target = dict(train_step.state)
    target["rng"] = jax.random.key_data(target["rng"])
    state = load_state(path, target=target, shardings=shardings)
    state["rng"] = jax.random.wrap_key_data(state["rng"])
    train_step.state = state
    return train_step
