"""Fleet orchestration (parity: python/paddle/distributed/fleet/base/
fleet_base.py — fleet.init:210, distributed_model:946,
distributed_optimizer, save_persistables:833).

TPU-first: ``fleet.init`` builds the hybrid mesh (topology.py);
``fleet.distributed_step`` is the load-bearing API — it assembles a pjit
TrainStep whose in/out shardings encode ALL the parallelisms at once:
batch over dp×sdp, TP specs from mp-annotated layers, ZeRO stage over sdp,
and remat. The reference's per-strategy model wrappers
(DataParallel/TensorParallel/PipelineParallel) + HybridParallelOptimizer
collapse into this one sharded compilation.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .env import get_rank, get_world_size, init_parallel_env
from .sharding import state_shardings
from .strategy import DistributedStrategy
from .topology import HybridCommunicateGroup


class Fleet:
    def __init__(self):
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._is_initialized = False

    # -- lifecycle ---------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None, devices=None):
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        tp = self._strategy.tensor_parallel_configs
        # tensor_parallel_configs is the non-hybrid way to ask for TP
        # (reference distributed_strategy.py tensor_parallel:1406): honor its
        # degree when hybrid_configs doesn't set one
        mp_degree = hc.mp_degree if hc.mp_degree > 1 else int(tp.tensor_parallel_degree)
        init_parallel_env()
        self._hcg = HybridCommunicateGroup(
            dp_degree=hc.dp_degree,
            mp_degree=mp_degree,
            pp_degree=hc.pp_degree,
            sharding_degree=hc.sharding_degree,
            sep_degree=hc.sep_degree,
            devices=devices,
        )
        if int(tp.tensor_init_seed) >= 0:
            # model-parallel RNG determinism (reference parallel_layers/
            # random.py RNGStatesTracker seeding)
            from .mp_layers import get_rng_state_tracker

            get_rng_state_tracker().add("model_parallel_rng", int(tp.tensor_init_seed))
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return get_rank() == 0

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def barrier_worker(self):
        return None

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def mesh(self):
        return self._hcg.mesh if self._hcg else None

    # -- model/optimizer wrappers (paddle API parity) ----------------------
    def distributed_model(self, model):
        """Parity: fleet_base.py:946. Under GSPMD no wrapper is needed —
        specs already live on the parameters; return the model unchanged."""
        model._fleet = self
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        optimizer._fleet = self
        return optimizer

    # -- the TPU-native training entry ------------------------------------
    def distributed_step(self, model, optimizer, loss_fn, seed=0, batch_sharding=None):
        """Build a sharded jit TrainStep per the active DistributedStrategy.

        Consumes every strategy knob: hybrid degrees (mesh), sharding stage
        (ZeRO specs), recompute, amp_configs (TrainStep amp_level/dtype),
        pipeline accumulate_steps (microbatch count for the pp trunk), and
        gradient_merge (lax.scan grad accumulation). Inputs default to
        batch-dim sharding over dp×sdp — the per-rank feed split the
        reference does in fleet/utils/hybrid_parallel_util.py:111.
        """
        from ..jit import TrainStep

        assert self._hcg is not None, "call fleet.init(strategy=...) first"
        mesh = self._hcg.mesh
        strat = self._strategy
        stage = strat.sharding_configs.sharding_stage if (strat.sharding or strat.hybrid_configs.sharding_degree > 1) else 0
        offload = bool(strat.sharding_configs.offload) and stage >= 1
        if not strat.sharding_configs.comm_overlap:
            import warnings

            warnings.warn(
                "sharding_configs.comm_overlap=False has no effect: XLA's "
                "latency-hiding scheduler always overlaps collectives with "
                "compute (the reference's manual comm/calc stream overlap is "
                "subsumed)")
        remat = strat.recompute or strat.recompute_configs.enable
        amp_level = strat.amp_configs.level if (strat.amp or strat.amp_configs.enable) else None
        amp_dtype = strat.amp_configs.dtype if amp_level else "bfloat16"
        if amp_level and str(amp_dtype) in ("float16", "fp16"):
            raise ValueError(
                "strategy amp with float16 needs loss scaling "
                f"(init_loss_scaling={strat.amp_configs.init_loss_scaling}, "
                f"dynamic={strat.amp_configs.use_dynamic_loss_scaling}) which "
                "the fused TrainStep does not implement — use bfloat16 "
                "(TPU-native, no scaling needed) or the eager amp.GradScaler "
                "path")
        accumulate = 1
        if strat.gradient_merge:
            accumulate = int(strat.gradient_merge_configs.get("k_steps", 1))
        elif strat.hybrid_configs.pp_degree == 1:
            # pipeline_configs.accumulate_steps doubles as grad accumulation
            # when there is no pipeline to microbatch (reference semantics)
            accumulate = int(strat.pipeline_configs.accumulate_steps)

        # mp/pp specs collected from annotated parameters
        mp_specs = {name: p.dist_spec for name, p in model.named_parameters() if getattr(p, "dist_spec", None) is not None}

        step = TrainStep(model, optimizer, loss_fn, remat=remat, seed=seed,
                         amp_level=amp_level, amp_dtype=amp_dtype, accumulate_steps=accumulate)
        shardings = state_shardings(step.state, mesh, stage=stage, mp_specs=mp_specs, offload=offload)
        if batch_sharding is None:
            # default: every batch leaf sharded on dim0 over the data axes
            batch_sharding = NamedSharding(mesh, P(("dp", "sdp")))
        step.mesh = mesh
        # place_state (not bare device_put): placement must own fresh
        # buffers, or the donated step deletes the model's own arrays
        # through an aliased replicated shard
        from .sharding import place_state

        step.state = place_state(step.state, shardings)
        step._jit = jax.jit(step._step, donate_argnums=0, in_shardings=(shardings, batch_sharding), out_shardings=(shardings, None))
        step.state_shardings = shardings
        # keep the TrainStep-internal copy in sync so the SPMD analyzer
        # (FLAGS_shard_check / explain(analyze=True)) sees the param specs
        step._state_shardings = shardings
        return step

    def shard_batch(self, *arrays):
        """Place a host batch sharded over the data axes (dp×sdp) —
        parity with the per-rank feed split in
        fleet/utils/hybrid_parallel_util.py:111."""
        import jax.numpy as jnp

        from ..framework.core import Tensor, unwrap

        mesh = self._hcg.mesh
        sh = NamedSharding(mesh, P(("dp", "sdp")))
        out = tuple(jax.device_put(jnp.asarray(unwrap(a)), sh) for a in arrays)
        return out if len(out) > 1 else out[0]

    # -- save/load (parity: fleet_base.py:795,833) -------------------------
    def save_persistables(self, executor_or_model, dirname, **kwargs):
        from ..framework.io import save

        model = executor_or_model
        save(model.state_dict(), f"{dirname}/model.pdparams")

    def save_inference_model(self, model, dirname, input_spec=None, **kwargs):
        from ..jit import save as jit_save

        jit_save(model, f"{dirname}/inference", input_spec=input_spec)


fleet = Fleet()
