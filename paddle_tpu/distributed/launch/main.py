"""Launcher implementation. See package docstring.

Reference call stack being replaced: launch/main.py:18 ``launch()`` →
context.Context → CollectiveController.run → watch() (controllers/
controller.py) and ElasticManager.watch (fleet/elastic/manager.py:577).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


class WorkerProc:
    def __init__(self, rank: int, proc: subprocess.Popen, log_path: Optional[str]):
        self.rank = rank
        self.proc = proc
        self.log_path = log_path


class LaunchContext:
    def __init__(self, args, script_args):
        self.args = args
        self.script_args = script_args


class CollectiveController:
    """Spawns + watches the local slice of a collective job (reference
    controllers/collective.py). One process per local slot; global ranks are
    node_rank * nproc_per_node + i."""

    def __init__(self, ctx: LaunchContext):
        self.ctx = ctx
        self.procs: List[WorkerProc] = []

    def _env_for(self, local_rank: int) -> dict:
        a = self.ctx.args
        rank = a.rank * a.nproc_per_node + local_rank
        world = a.nnodes * a.nproc_per_node
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": a.master,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_NNODES": str(a.nnodes),
            "FLAGS_selected_devices": str(local_rank),
        })
        if a.devices:
            env["CUDA_VISIBLE_DEVICES"] = a.devices  # accepted for API parity
        return env

    def spawn(self):
        a = self.ctx.args
        self.procs = []
        for i in range(a.nproc_per_node):
            log_path = None
            stdout = None
            if a.log_dir:
                os.makedirs(a.log_dir, exist_ok=True)
                rank = a.rank * a.nproc_per_node + i
                log_path = os.path.join(a.log_dir, f"worker.{rank}.log")
                stdout = open(log_path, "ab")
            cmd = [sys.executable, "-u", self.ctx.args.training_script] + self.ctx.script_args
            proc = subprocess.Popen(cmd, env=self._env_for(i), stdout=stdout, stderr=subprocess.STDOUT if stdout else None)
            self.procs.append(WorkerProc(a.rank * a.nproc_per_node + i, proc, log_path))

    def poll(self):
        """(still_running, failed_ranks, done)"""
        failed, running = [], 0
        for w in self.procs:
            rc = w.proc.poll()
            if rc is None:
                running += 1
            elif rc != 0:
                failed.append(w.rank)
        return running, failed, running == 0 and not failed

    def terminate(self, sig=signal.SIGTERM, grace=5.0):
        for w in self.procs:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(sig)
                except OSError:
                    pass
        t0 = time.time()
        while time.time() - t0 < grace and any(w.proc.poll() is None for w in self.procs):
            time.sleep(0.1)
        for w in self.procs:
            if w.proc.poll() is None:
                w.proc.kill()
        for w in self.procs:
            w.proc.wait()

    def watch(self, interval=0.5) -> int:
        """Block until all workers exit; on any failure terminate the rest.
        Returns 0 on success, first failing signal/code otherwise."""
        while True:
            running, failed, done = self.poll()
            if failed:
                self.terminate()
                return 1
            if done:
                return 0
            time.sleep(interval)


class ElasticManager:
    """Minimal elastic loop (reference fleet/elastic/manager.py:131,577):
    when a worker dies, tear the job down and relaunch the whole collective
    — membership changes restart the world, training resumes from the
    user's own checkpoints."""

    def __init__(self, controller: CollectiveController, max_restarts: int):
        self.controller = controller
        self.max_restarts = max_restarts
        self.restarts = 0

    def run(self, interval=0.5) -> int:
        self.controller.spawn()
        while True:
            rc = self.controller.watch(interval)
            if rc == 0:
                return 0
            if self.restarts >= self.max_restarts:
                print(f"[launch] worker failed; restart budget ({self.max_restarts}) exhausted", file=sys.stderr)
                return rc
            self.restarts += 1
            print(f"[launch] worker failed; elastic restart {self.restarts}/{self.max_restarts}", file=sys.stderr)
            self.controller.terminate()
            self.controller.spawn()


def _parser():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch", description="multi-host collective launcher (reference launch/main.py parity)")
    p.add_argument("--nnodes", type=int, default=1, help="number of nodes (hosts)")
    p.add_argument("--nproc_per_node", type=int, default=1, help="worker processes per node (1 per TPU host is canonical)")
    p.add_argument("--rank", type=int, default=int(os.environ.get("PADDLE_NODE_RANK", "0")), help="this node's rank")
    p.add_argument("--master", type=str, default=os.environ.get("PADDLE_MASTER", "127.0.0.1:49175"), help="coordinator host:port (rank-0 node)")
    p.add_argument("--log_dir", type=str, default=None, help="per-worker log directory")
    p.add_argument("--devices", "--gpus", type=str, default=None, help="device selection (parity flag)")
    p.add_argument("--elastic_retries", type=int, default=0, help="relaunch the collective up to N times on worker failure")
    p.add_argument("training_script", type=str)
    return p


def launch(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    ns, script_args = _parser().parse_known_args(argv)
    ctx = LaunchContext(ns, script_args)
    controller = CollectiveController(ctx)
    if ns.elastic_retries > 0:
        return ElasticManager(controller, ns.elastic_retries).run()
    controller.spawn()
    return controller.watch()


def main():
    sys.exit(launch())
