"""Launcher implementation. See package docstring.

Reference call stack being replaced: launch/main.py:18 ``launch()`` →
context.Context → CollectiveController.run → watch() (controllers/
controller.py) and ElasticManager.watch (fleet/elastic/manager.py:577).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


class WorkerProc:
    def __init__(self, rank: int, proc: subprocess.Popen, log_path: Optional[str]):
        self.rank = rank
        self.proc = proc
        self.log_path = log_path


class LaunchContext:
    def __init__(self, args, script_args):
        self.args = args
        self.script_args = script_args


class CollectiveController:
    """Spawns + watches the local slice of a collective job (reference
    controllers/collective.py). One process per local slot; global ranks are
    node_rank * nproc_per_node + i."""

    def __init__(self, ctx: LaunchContext):
        self.ctx = ctx
        self.procs: List[WorkerProc] = []

    def _env_for(self, local_rank: int, nnodes=None, node_rank=None) -> dict:
        a = self.ctx.args
        nnodes = a.nnodes if nnodes is None else nnodes
        node_rank = a.rank if node_rank is None else node_rank
        rank = node_rank * a.nproc_per_node + local_rank
        world = nnodes * a.nproc_per_node
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": a.master,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_NNODES": str(nnodes),
            "FLAGS_selected_devices": str(local_rank),
        })
        if a.devices:
            env["CUDA_VISIBLE_DEVICES"] = a.devices  # accepted for API parity
        return env

    def spawn(self, nnodes=None, node_rank=None):
        a = self.ctx.args
        base = (a.rank if node_rank is None else node_rank) * a.nproc_per_node
        self.procs = []
        for i in range(a.nproc_per_node):
            log_path = None
            stdout = None
            if a.log_dir:
                os.makedirs(a.log_dir, exist_ok=True)
                log_path = os.path.join(a.log_dir, f"worker.{base + i}.log")
                stdout = open(log_path, "ab")
            cmd = [sys.executable, "-u", self.ctx.args.training_script] + self.ctx.script_args
            proc = subprocess.Popen(cmd, env=self._env_for(i, nnodes, node_rank), stdout=stdout, stderr=subprocess.STDOUT if stdout else None)
            self.procs.append(WorkerProc(base + i, proc, log_path))

    def poll(self):
        """(still_running, failed_ranks, done)"""
        failed, running = [], 0
        for w in self.procs:
            rc = w.proc.poll()
            if rc is None:
                running += 1
            elif rc != 0:
                failed.append(w.rank)
        return running, failed, running == 0 and not failed

    def terminate(self, sig=signal.SIGTERM, grace=5.0):
        for w in self.procs:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(sig)
                except OSError:
                    pass
        t0 = time.time()
        while time.time() - t0 < grace and any(w.proc.poll() is None for w in self.procs):
            time.sleep(0.1)
        for w in self.procs:
            if w.proc.poll() is None:
                w.proc.kill()
        for w in self.procs:
            w.proc.wait()

    def watch(self, interval=0.5, tick=None) -> int:
        """Block until all workers exit; on any failure terminate the rest.
        Returns 0 on success, first failing signal/code otherwise. ``tick``
        (if given) is called each poll; if it returns a non-None value the
        watch stops and returns it (elastic membership interrupts)."""
        while True:
            running, failed, done = self.poll()
            if failed:
                self.terminate()
                return 1
            if done:
                return 0
            if tick is not None:
                r = tick()
                if r is not None:
                    return r
            time.sleep(interval)


class ElasticManager:
    """Fixed-world elastic loop (reference fleet/elastic/manager.py:131):
    when a worker dies, tear the job down and relaunch the whole collective
    — membership changes restart the world, training resumes from the
    user's own checkpoints."""

    def __init__(self, controller: CollectiveController, max_restarts: int):
        self.controller = controller
        self.max_restarts = max_restarts
        self.restarts = 0

    def run(self, interval=0.5) -> int:
        self.controller.spawn()
        while True:
            rc = self.controller.watch(interval)
            if rc == 0:
                return 0
            if self.restarts >= self.max_restarts:
                print(f"[launch] worker failed; restart budget ({self.max_restarts}) exhausted", file=sys.stderr)
                return rc
            self.restarts += 1
            print(f"[launch] worker failed; elastic restart {self.restarts}/{self.max_restarts}", file=sys.stderr)
            self.controller.terminate()
            self.controller.spawn()


class ElasticMembershipManager:
    """True elasticity (reference ElasticManager watch loop,
    fleet/elastic/manager.py:577): TCPStore-heartbeat membership, HOLD on
    join/leave, RESTART with rescaled node ranks when the alive set settles
    inside the allowed np range. Training scripts resume from their own
    checkpoints (the reference contract)."""

    def __init__(self, controller: CollectiveController, np_range, max_restarts=10,
                 heartbeat_interval=0.5, node_timeout=3.0):
        from ..elastic import ElasticNode
        from ..store import TCPStore

        self.controller = controller
        self.min_np, self.max_np = np_range
        self.max_restarts = max_restarts
        a = controller.ctx.args
        host, port = a.master.rsplit(":", 1)
        # port map: <master> itself is the workers' jax.distributed
        # coordinator, +1 is init_parallel_env's bootstrap store (env.py) —
        # the membership registry takes +2 to collide with neither.
        # The node with --rank 0 hosts it; others connect (reference: etcd).
        self.store = TCPStore(host=host, port=int(port) + 2, is_master=(a.rank == 0),
                              world_size=a.nnodes, timeout=60.0)
        self.node = ElasticNode(self.store, heartbeat_interval, node_timeout)
        self.restarts = 0

    def run(self, interval=0.3) -> int:
        members = self.node.wait_for(self.min_np, self.max_np)
        while True:
            if self.node.node_id not in members:
                members = self.node.wait_for(self.min_np, self.max_np)
                continue
            nnodes = len(members)
            node_rank = members.index(self.node.node_id)
            print(f"[launch][elastic] membership={members} -> nnodes={nnodes} "
                  f"node_rank={node_rank}", file=sys.stderr, flush=True)
            self.controller.spawn(nnodes=nnodes, node_rank=node_rank)

            cur = members

            def membership_tick():
                # any membership change → HOLD (terminate + settle + respawn;
                # below-min worlds simply keep waiting inside wait_for)
                if self.node.alive_nodes() != cur:
                    return 100
                return None

            rc = self.controller.watch(interval, tick=membership_tick)
            if rc == 0:
                self.node.leave()
                return 0
            self.controller.terminate()
            if rc != 100:  # genuine worker failure, not a membership event
                if self.restarts >= self.max_restarts:
                    print(f"[launch][elastic] restart budget ({self.max_restarts}) exhausted", file=sys.stderr)
                    self.node.leave()
                    return rc
                self.restarts += 1
            # HOLD → settle → RESTART with rescaled ranks
            members = self.node.wait_for(self.min_np, self.max_np)


class ServeController(CollectiveController):
    """``--serve``: every worker slot hosts one cross-process serving
    replica (``python -m paddle_tpu.inference.procfleet``) instead of a
    training script. The rank-0 node hosts the fleet TCPStore at
    ``--master``; replicas connect to it, register store membership
    (``procfleet/<ns>/members_n`` + their heartbeat key), and idle until a
    serving front adopts them via ``ProcServingFleet.attach(master, ns=ns)``.
    The positional argument is a JSON spec file::

        {"ns": "serve", "model": {"seed": 0, "config": {...GPTConfig kwargs}},
         "engine_kwargs": {"max_batch_slots": 2, ...}, "beat_interval": 0.05}

    A front-end ``shutdown()`` drains every replica (exit 0), so
    ``watch()`` returns 0 and the launcher exits clean."""

    def __init__(self, ctx: LaunchContext, spec: dict):
        super().__init__(ctx)
        self.spec = dict(spec)
        self.store = None

    def host_store(self):
        a = self.ctx.args
        if a.rank != 0:
            return
        from ..store import TCPStore

        host, port = a.master.rsplit(":", 1)
        self.store = TCPStore(host=host, port=int(port), is_master=True,
                              world_size=1, timeout=60.0)

    def spawn(self, nnodes=None, node_rank=None):
        import json

        from ...inference.procfleet import (CHILD_CMD, SPEC_ENV, child_env,
                                            current_jax_config)

        a = self.ctx.args
        base = (a.rank if node_rank is None else node_rank) * a.nproc_per_node
        self.procs = []
        for i in range(a.nproc_per_node):
            rid = base + i
            spec = dict(self.spec)
            spec.setdefault("ns", "serve")  # noqa: PTA104 (host-side, never traced)
            spec.setdefault("jax_config", current_jax_config())  # noqa: PTA104 (host-side, never traced)
            spec.update({"rid": rid, "endpoint": a.master})  # noqa: PTA104 (host-side, never traced)
            # trainer id 0 is the serving front (the attach() parent);
            # replicas take 1..N so trace/span id streams decorrelate
            env = child_env({SPEC_ENV: json.dumps(spec),
                             "PADDLE_TRAINER_ID": str(rid + 1)})
            log_path = None
            stdout = None
            if a.log_dir:
                os.makedirs(a.log_dir, exist_ok=True)
                log_path = os.path.join(a.log_dir, f"replica.{rid}.log")
                stdout = open(log_path, "ab")
            proc = subprocess.Popen(CHILD_CMD, env=env, stdout=stdout,
                                    stderr=subprocess.STDOUT if stdout else None)
            self.procs.append(WorkerProc(rid, proc, log_path))  # noqa: PTA104 (host-side, never traced)


def _serve(ns, script_args) -> int:
    import json

    spec = {}
    if ns.training_script:
        with open(ns.training_script) as f:
            spec = json.load(f)
    controller = ServeController(LaunchContext(ns, script_args), spec)
    controller.host_store()
    try:
        controller.spawn()
        print(f"[launch][serve] {ns.nproc_per_node} replica(s) on node "  # noqa: PTA105 (host-side, never traced)
              f"{ns.rank}; store endpoint {ns.master} ns "
              f"{spec.get('ns', 'serve')!r} — attach with "
              f"ProcServingFleet.attach({ns.master!r})",
              file=sys.stderr, flush=True)
        if ns.http is not None and ns.rank == 0:
            return _serve_http(ns, spec, controller)
        return controller.watch()
    finally:
        if controller.store is not None:
            try:
                controller.store.close()
            except OSError:
                pass


def _serve_http(ns, spec, controller) -> int:
    """``--serve --http PORT``: rank 0 also runs the front end — adopt the
    replicas it just spawned (``ProcServingFleet.attach``) and put a
    :class:`~...inference.ingress.ServingIngress` in front. SIGTERM drains
    the ingress gracefully (finish in-flight, then shut the fleet down) and
    the launcher exits 0."""
    from ...inference.ingress import ServingIngress
    from ...inference.procfleet import ProcServingFleet

    fleet = ProcServingFleet.attach(
        ns.master, replicas=ns.nnodes * ns.nproc_per_node,
        ns=spec.get("ns", "serve"),
        boot_timeout=float(spec.get("boot_timeout", 120.0)))
    ingress = ServingIngress(fleet, port=ns.http)
    print(f"[launch][serve] ingress on {ingress.url} "  # noqa: PTA105 (host-side, never traced)
          f"(POST /v1/generate, GET /healthz)", file=sys.stderr, flush=True)
    try:
        rc = ingress.serve_until_drained()
    finally:
        fleet.shutdown()
    return rc


def _parser():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch", description="multi-host collective launcher (reference launch/main.py parity)")
    p.add_argument("--nnodes", type=int, default=1, help="number of nodes (hosts)")
    p.add_argument("--nproc_per_node", type=int, default=1, help="worker processes per node (1 per TPU host is canonical)")
    p.add_argument("--rank", type=int, default=int(os.environ.get("PADDLE_NODE_RANK", "0")), help="this node's rank")
    p.add_argument("--master", type=str, default=os.environ.get("PADDLE_MASTER", "127.0.0.1:49175"), help="coordinator host:port (rank-0 node)")
    p.add_argument("--log_dir", type=str, default=None, help="per-worker log directory")
    p.add_argument("--devices", "--gpus", type=str, default=None, help="device selection (parity flag)")
    p.add_argument("--elastic_retries", type=int, default=0, help="relaunch the collective up to N times on worker failure")
    p.add_argument("--elastic_np", type=str, default=os.environ.get("PADDLE_ELASTIC_NP"), help="elastic node range 'min:max' (or 'n'): membership-managed launch with rescaling")
    p.add_argument("--elastic_timeout", type=float, default=3.0, help="heartbeat staleness (s) before a node is considered gone")
    p.add_argument("--serve", action="store_true", help="boot cross-process serving replicas (paddle_tpu.inference.procfleet) instead of a training script; the positional argument is the fleet spec JSON (model config + engine kwargs), rank 0 hosts the store at --master, and a front-end adopts the fleet with ProcServingFleet.attach")
    p.add_argument("--http", type=int, default=None, metavar="PORT", help="with --serve: rank 0 also attaches the fleet and runs the HTTP ingress (ServingIngress) on PORT; SIGTERM drains gracefully and exits 0")
    p.add_argument("training_script", type=str)
    return p


def launch(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    ns, script_args = _parser().parse_known_args(argv)
    if ns.serve:
        return _serve(ns, script_args)
    ctx = LaunchContext(ns, script_args)
    controller = CollectiveController(ctx)
    if ns.elastic_np:
        from ..elastic import parse_np_range

        return ElasticMembershipManager(
            controller, parse_np_range(ns.elastic_np),
            max_restarts=ns.elastic_retries or 10,
            node_timeout=ns.elastic_timeout).run()
    if ns.elastic_retries > 0:
        return ElasticManager(controller, ns.elastic_retries).run()
    controller.spawn()
    return controller.watch()


def main():
    sys.exit(launch())
