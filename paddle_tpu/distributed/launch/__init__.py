"""paddle.distributed.launch parity (reference:
python/paddle/distributed/launch/main.py:18 + controllers/collective.py +
fleet/elastic/manager.py:131).

``python -m paddle_tpu.distributed.launch --nnodes N train.py`` spawns one
worker process per node slot, wires the TCPStore/coordinator rendezvous env
(consumed by distributed/env.py init_parallel_env), watches the fleet, and
— with ``--elastic_retries`` — restarts the whole job on worker failure
(the reference ElasticManager's watch/restart loop, minus etcd: the
membership store is the launcher itself)."""
from .main import launch, main  # noqa: F401
