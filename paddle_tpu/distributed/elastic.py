"""Elastic membership: TCPStore heartbeats + watch loop + rank rescaling.

Parity: fleet/elastic/manager.py — ElasticManager (init:131) keeps node
membership in etcd (heartbeat lease per node), its watch loop (:577)
detects join/leave and answers HOLD (pause) → RESTART with **rescaled
ranks** when membership settles inside the allowed np range.

TPU-first: etcd is replaced by the repo's own native TCPStore
(csrc/tcp_store.cc). A node's identity is an atomic counter ticket
(``store.add``); liveness is a timestamp key refreshed by a daemon thread;
membership = tickets whose timestamp is fresh. The launcher-side manager
(launch/main.py) terminates local workers on any membership change and
respawns them with recomputed PADDLE_NNODES / node rank — training resumes
from the job's checkpoints (hapi ModelCheckpoint or manual save/load).
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from ..testing import chaos

_PREFIX = "elastic"


class ElasticNode:
    """This host's membership handle: registers a node ticket and keeps its
    heartbeat fresh; can enumerate the alive set."""

    def __init__(self, store, heartbeat_interval: float = 0.5, timeout: float = 3.0):
        self.store = store
        self.interval = heartbeat_interval
        self.timeout = timeout
        self.node_id = store.add(f"{_PREFIX}/next_id", 1) - 1
        self._beat()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _beat(self):
        if chaos.heartbeat_frozen(self.node_id):
            return  # injected zombie: process lives, membership sees it die
        self.store.set(f"{_PREFIX}/hb/{self.node_id}", repr(time.time()))

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self._beat()
            except OSError:
                return  # store gone (job teardown)

    def leave(self):
        """Graceful departure: stop beating and mark the ticket dead."""
        self._stop.set()
        try:
            self.store.set(f"{_PREFIX}/hb/{self.node_id}", "0.0")
        except OSError:
            pass

    def alive_nodes(self) -> List[int]:
        """Ticket ids with a fresh heartbeat, ascending (their index in this
        list is the node's rescaled rank — reference manager re-sorts hosts
        the same way on RESTART)."""
        n = self.store.add(f"{_PREFIX}/next_id", 0)
        now = time.time()
        alive = []
        for i in range(n):
            try:
                ts = float(self.store.get(f"{_PREFIX}/hb/{i}", timeout=0.25))
            except (TimeoutError, ValueError, OSError):
                continue
            if now - ts < self.timeout:
                alive.append(i)
        return alive

    def wait_for(self, min_nodes: int, max_nodes: Optional[int] = None,
                 settle: float = 1.0, deadline: float = 60.0) -> List[int]:
        """Block until the alive set has >= min_nodes and is stable for
        ``settle`` seconds (the reference's HOLD debounce before RESTART)."""
        t0 = time.time()
        last, last_change = None, time.time()
        while True:
            cur = self.alive_nodes()
            if cur != last:
                last, last_change = cur, time.time()
            ok_count = len(cur) >= min_nodes and (max_nodes is None or len(cur) <= max_nodes)
            if ok_count and time.time() - last_change >= settle:
                return cur
            if time.time() - t0 > deadline:
                raise TimeoutError(
                    f"elastic: membership never reached [{min_nodes}, {max_nodes}] "
                    f"(alive={cur}) within {deadline}s")
            time.sleep(self.interval)


def parse_np_range(spec: str) -> Tuple[int, Optional[int]]:
    """'2' -> (2, 2); '1:4' -> (1, 4) (reference --np / PADDLE_ELASTIC_NP)."""
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return int(lo), (int(hi) if hi else None)
    n = int(spec)
    return n, n
