"""DistributedStrategy (parity: protobuf-backed config in
python/paddle/distributed/fleet/base/distributed_strategy.py; proto
paddle/fluid/framework/distributed_strategy.proto).

A typed dataclass tree instead of protobuf; the same knobs: hybrid degrees
(hybrid_configs:1437), sharding (1148), amp (718), recompute (805), pipeline
micro-batching (1345), tensor_parallel (1406).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1  # sequence/context parallel (green-field; absent in ref)
    ep_degree: int = 1  # expert parallel


@dataclass
class ShardingConfig:
    sharding_stage: int = 1  # ZeRO stage 1/2/3
    offload: bool = False
    comm_overlap: bool = True


@dataclass
class AmpConfig:
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O1"
    init_loss_scaling: float = 32768.0
    use_dynamic_loss_scaling: bool = True


@dataclass
class RecomputeConfig:
    enable: bool = False
    checkpoints: Optional[list] = None


@dataclass
class PipelineConfig:
    accumulate_steps: int = 1  # micro-batches
    schedule: str = "gpipe"  # gpipe | 1f1b (memory schedule hint)


@dataclass
class TensorParallelConfig:
    tensor_parallel_degree: int = 1
    tensor_init_seed: int = -1


@dataclass
class DistributedStrategy:
    hybrid_configs: HybridConfig = field(default_factory=HybridConfig)
    sharding_configs: ShardingConfig = field(default_factory=ShardingConfig)
    amp_configs: AmpConfig = field(default_factory=AmpConfig)
    recompute_configs: RecomputeConfig = field(default_factory=RecomputeConfig)
    pipeline_configs: PipelineConfig = field(default_factory=PipelineConfig)
    tensor_parallel_configs: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    amp: bool = False
    recompute: bool = False
    sharding: bool = False
    gradient_merge: bool = False
    gradient_merge_configs: dict = field(default_factory=lambda: {"k_steps": 1})
    find_unused_parameters: bool = False

    def __post_init__(self):
        pass

    def _set(self, name, value):
        # paddle lets users assign dicts to *_configs; accept both
        if isinstance(value, dict):
            cfg = getattr(self, name)
            for k, v in value.items():
                setattr(cfg, k, v)
        else:
            object.__setattr__(self, name, value)

    def __setattr__(self, name, value):
        if name.endswith("_configs") and isinstance(value, dict) and hasattr(self, name):
            cfg = getattr(self, name)
            for k, v in value.items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            return
        object.__setattr__(self, name, value)
