"""ZeRO sharding policies (parity: the group_sharded stack —
GroupShardedOptimizerStage2 fleet/meta_parallel/sharding/
group_sharded_optimizer_stage2.py:48, GroupShardedStage3
group_sharded_stage3.py:60, public API
python/paddle/distributed/sharding/group_sharded.py).

TPU-first: a "stage" is a PartitionSpec policy over the 'sdp' mesh axis:
  stage 1 — optimizer state sharded; params/grads replicated
  stage 2 — + grads effectively reduce-scattered (XLA picks the pattern
             from the sharded opt-state output specs)
  stage 3 — + params sharded; forward all-gathers weights on demand
The reference's rank-sliced grad storage, param hooks and manual
broadcast/allgather (group_sharded_stage3.py:399-425) all become these specs.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _extend_spec(spec: Optional[P], shape, axis_size: int, axis_name="sdp", min_size=16384, mesh=None) -> P:
    """Add ``axis_name`` (ZeRO) sharding to a param/opt spec.

    Preference order:
    1. Compose with an already-sharded dim: a dim carrying 'mp' becomes
       ('mp', 'sdp'). This keeps the ZeRO split aligned with the TP split,
       so grads reduce-scatter along the dim that is already model-parallel
       — sharding a *fresh* (hidden) dim instead pulls activations toward
       hidden-sharded layouts and triggers XLA's "Involuntary full
       rematerialization" reshards (VERDICT r2 bug).
    2. Otherwise the largest unsharded dim divisible by axis_size.
    Small params stay replicated."""
    base = list(spec) if spec is not None else [None] * len(shape)
    while len(base) < len(shape):
        base.append(None)

    def canon(b):
        while b and b[-1] is None:
            b.pop()
        return P(*b)

    if axis_size <= 1 or int(np.prod(shape)) < min_size:
        return canon(base)

    def axes_of(entry):
        if entry is None:
            return ()
        return tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)

    def size_of(axes):
        return int(np.prod([mesh.shape.get(a, 1) for a in axes]))

    # 1. compose with an existing model-parallel dim ('pp' stacking axes are
    #    layer indices, not tensor dims to subdivide further)
    for i in range(len(shape)):
        ax = axes_of(base[i])
        if ax and "pp" not in ax and axis_name not in ax:
            existing = size_of(ax) if mesh is not None else 0
            if existing and shape[i] % (existing * axis_size) == 0:
                base[i] = ax + (axis_name,)
                return canon(base)
    # 2. a fresh dim
    cand = [
        (shape[i], i)
        for i in range(len(shape))
        if base[i] is None and shape[i] % axis_size == 0
    ]
    if not cand:
        return canon(base)
    _, dim = max(cand)
    base[dim] = axis_name
    return canon(base)


def build_state_specs(params: Dict[str, np.ndarray], mesh: Mesh, stage: int = 1, mp_specs: Optional[Dict[str, P]] = None, opt_state_keys=("m", "v", "u", "velocity", "moment", "mean_square", "mean_grad", "avg_sq_grad", "avg_sq_update")):
    """Return (param_specs, opt_specs_fn) for a TrainStep state tree."""
    sdp = mesh.shape.get("sdp", 1)
    mp_specs = mp_specs or {}
    param_specs = {}
    opt_specs = {}
    for name, arr in params.items():
        base = mp_specs.get(name)
        shape = tuple(arr.shape)
        if stage >= 3:
            spec = _extend_spec(base, shape, sdp, mesh=mesh)
        else:
            spec = P(*base) if base is not None else P()
        param_specs[name] = spec
        if stage >= 1:
            opt_specs[name] = _extend_spec(base, shape, sdp, mesh=mesh)
        else:
            opt_specs[name] = spec
    return param_specs, opt_specs


def state_shardings(state, mesh: Mesh, stage: int = 1, mp_specs=None, offload=False):
    """Shardings pytree matching a TrainStep state dict.

    ``offload=True`` is ZeRO-offload parity (reference
    group_sharded_optimizer_stage2.py ``offload=True`` keeps optimizer state
    in host memory): optimizer-state shardings get
    ``memory_kind='pinned_host'`` — XLA stages the m/v tensors in host RAM
    and streams them through the fused update. Falls back to device memory
    (with a warning) on backends without host memory spaces."""
    params = state["params"]
    param_specs, opt_specs = build_state_specs(params, mesh, stage, mp_specs)

    def ns(spec):
        return NamedSharding(mesh, spec)

    def ns_opt(spec):
        if offload:
            try:
                return NamedSharding(mesh, spec, memory_kind="pinned_host")
            except (ValueError, TypeError):
                import warnings

                warnings.warn("sharding offload=True: backend has no pinned_host "
                              "memory space; optimizer state stays in device memory")
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, spec)

    # opt state: dict of moment-name -> {param-name: array}
    opt_shard = {}
    for moment_name, tree in state["opt"].items():
        opt_shard[moment_name] = {k: ns_opt(opt_specs.get(k, P())) for k in tree}
    return {
        "params": {k: ns(s) for k, s in param_specs.items()},
        "buffers": {k: ns(P()) for k in state["buffers"]},
        "opt": opt_shard,
        "step": ns(P()),
        "rng": ns(P()),
    }


def place_state(state, shardings):
    """``jax.device_put(state, shardings)`` without buffer aliasing.

    A plain ``device_put`` may *reuse* the source buffer as one shard of
    the placed array (replicated leaves on the source device). A TrainStep
    then donates that buffer on its first dispatch — deleting the model's
    own parameter array out from under any later rebuild
    (``planner.build_step`` during an elastic re-plan reads
    ``model.param_arrays()`` again). Round-tripping through host bytes
    guarantees the placed state owns fresh buffers. Typed PRNG keys (no
    numpy spelling) go through a plain ``device_put`` — they are created
    fresh per TrainStep, so nothing else holds their buffer.
    """
    import jax

    def fresh(leaf, sh):
        try:
            host = np.asarray(jax.device_get(leaf))
        except TypeError:  # extended dtype: typed PRNG key
            return jax.device_put(leaf, sh)
        return jax.device_put(host, sh)

    return jax.tree_util.tree_map(fresh, state, shardings)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None, group=None, offload=False, sync_buffers=False, buffer_max_size=2**23, segment_size=2**20, sync_comm=False):
    """API parity (python/paddle/distributed/sharding/group_sharded.py).
    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3). Returns the
    pair unchanged plus records the stage for fleet.distributed_step."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    model._sharding_stage = stage
    optimizer._sharding_stage = stage
    model._sharding_offload = optimizer._sharding_offload = bool(offload)
    return model, optimizer, scaler
