"""Ring attention — sequence/context parallelism.

Green-field feature (SURVEY.md §2.3: ABSENT in the reference snapshot; the
ref bounds sequence length by single-device memory × TP head sharding).
Design: Q/K/V sharded over the 'sep' mesh axis on the sequence dim inside
``shard_map``; K/V blocks rotate around the ring with ``lax.ppermute`` while
each device accumulates its queries' attention with an online softmax —
compute overlaps the ICI transfer of the next block (XLA pipelines the
ppermute against the matmuls). Causal masking uses global positions.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _block_attn(q, k, v, q_off, k_off, causal, scale):
    """One block's contribution with running-softmax stats.

    q: [B,H,Sq,D]; k/v: [B,H,Sk,D]. Returns (num, denom, m) pieces.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Sq]
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o, l, jnp.where(jnp.isfinite(m), m, -jnp.inf)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sep", causal: bool = True):
    """q/k/v: [batch, seq, heads, dim] with seq sharded over ``axis``.

    Returns same-shaped output, seq-sharded the same way.
    """
    n_dev = mesh.shape[axis]
    scale = 1.0 / (q.shape[-1] ** 0.5)

    def local(qs, ks, vs):
        # local shapes: [B, S/n, H, D] -> [B,H,S/n,D]
        ql = jnp.swapaxes(qs, 1, 2)
        kl = jnp.swapaxes(ks, 1, 2)
        vl = jnp.swapaxes(vs, 1, 2)
        seq_local = ql.shape[2]
        idx = jax.lax.axis_index(axis)
        q_off = idx * seq_local
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        # running accumulators (flash-style)
        acc = jnp.zeros(ql.shape, jnp.float32)
        denom = jnp.zeros(ql.shape[:3], jnp.float32)
        m_run = jnp.full(ql.shape[:3], -jnp.inf, jnp.float32)

        def step(i, carry):
            acc, denom, m_run, kb, vb, k_owner = carry
            k_off = k_owner * seq_local
            o, l, m = _block_attn(ql, kb, vb, q_off, k_off, causal, scale)
            m_new = jnp.maximum(m_run, m)
            m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            alpha = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - m_new_safe), 0.0)
            beta = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new_safe), 0.0)
            acc = acc * alpha[..., None] + o * beta[..., None]
            denom = denom * alpha + l * beta
            # rotate K/V to the next device; owner index rotates with them
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            k_owner = jax.lax.ppermute(k_owner, axis, perm)
            return acc, denom, jnp.maximum(m_run, m), kb, vb, k_owner

        carry = (acc, denom, m_run, kl, vl, idx)
        for i in range(n_dev):  # static unroll: n_dev is small; XLA overlaps
            carry = step(i, carry)
        acc, denom, m_run = carry[0], carry[1], carry[2]
        out = acc / jnp.maximum(denom[..., None], 1e-30)
        return jnp.swapaxes(out.astype(qs.dtype), 1, 2)

    mapped = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis, None, None), P(None, axis, None, None), P(None, axis, None, None)),
        out_specs=P(None, axis, None, None),
        check_vma=False,
    )
    return mapped(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sep", causal: bool = True):
    """DeepSpeed-Ulysses style: all_to_all seq-shard -> head-shard, full
    attention locally, all_to_all back. Cheaper than ring when heads >= sep
    degree; green-field (absent in reference)."""
    n = mesh.shape[axis]

    def local(qs, ks, vs):
        # [B, S/n, H, D] -> exchange so each device holds H/n heads, full S
        def seq2head(x):
            x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
            return x

        def head2seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq2head(qs), seq2head(ks), seq2head(vs)
        from ..nn.functional.attention import _sdpa_reference

        out = _sdpa_reference(qh, kh, vh, mask=None, causal=causal)
        return head2seq(out)

    mapped = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis, None, None),) * 3,
        out_specs=P(None, axis, None, None),
        check_vma=False,
    )
    return mapped(q, k, v)
