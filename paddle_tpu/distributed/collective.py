"""Functional collectives (parity: python/paddle/distributed/collective.py —
all_reduce:618, all_gather:840, alltoall:1769, broadcast:533, etc).

TPU-first semantics: these are *traced* collectives for use inside
``shard_map`` regions over mesh axes (the manual-SPMD escape hatch). In the
pjit/GSPMD path you normally never call them — sharding annotations make XLA
insert them. The reference's three-way branch (eager ProcessGroup / legacy
c_* op / static append_op) collapses to jax.lax collectives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, _wrap_value, unwrap
from ..observability import span as _span
from ..observability.metrics import counter_inc as _counter_inc


def _collective(name):
    """Telemetry wrapper: every collective entry bumps
    ``collective.<name>.calls`` and runs under a ``collective.<name>`` span.
    Inside a shard_map/jit trace the span measures trace time (the dispatch
    XLA sees); for eager concrete arrays it covers the actual execution."""

    def deco(fn):
        import functools

        counter = f"collective.{name}.calls"
        span_name = f"collective.{name}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            _counter_inc(counter)
            with _span(span_name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _axis(group):
    if group is None:
        return "dp"
    if isinstance(group, str):
        return group
    return getattr(group, "axis_name", "dp")


@_collective("all_reduce")
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis(group)
    v = unwrap(tensor)
    if op == ReduceOp.SUM:
        out = jax.lax.psum(v, ax)
    elif op == ReduceOp.MAX:
        out = jax.lax.pmax(v, ax)
    elif op == ReduceOp.MIN:
        out = jax.lax.pmin(v, ax)
    elif op == ReduceOp.AVG:
        out = jax.lax.pmean(v, ax)
    else:
        raise ValueError(f"unsupported reduce op {op}")
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return out


@_collective("all_gather")
def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    ax = _axis(group)
    v = unwrap(tensor if tensor is not None else tensor_list)
    out = jax.lax.all_gather(v, ax, tiled=False)
    if isinstance(tensor_list, list):
        n = out.shape[0]
        tensor_list.clear()
        tensor_list.extend(_wrap_value(out[i]) for i in range(n))
        return tensor_list
    return out


@_collective("all_gather_concat")
def all_gather_concat(x, group=None, concat_axis=0):
    ax = _axis(group)
    return jax.lax.all_gather(unwrap(x), ax, axis=concat_axis, tiled=True)


@_collective("reduce_scatter")
def reduce_scatter(output, input, op=ReduceOp.SUM, group=None, sync_op=True, scatter_axis=0):
    ax = _axis(group)
    v = unwrap(input)
    out = jax.lax.psum_scatter(v, ax, scatter_dimension=scatter_axis, tiled=True)
    if isinstance(output, Tensor):
        output._value = out
        return output
    return out


@_collective("alltoall")
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True, split_axis=0, concat_axis=0):
    ax = _axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        v = jnp.stack([unwrap(t) for t in in_tensor_list])
        out = jax.lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=False)
        if out_tensor_list is not None:
            out_tensor_list.clear()
            out_tensor_list.extend(_wrap_value(out[i]) for i in range(out.shape[0]))
            return out_tensor_list
        return out
    return jax.lax.all_to_all(unwrap(in_tensor_list), ax, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


all_to_all = alltoall


@_collective("broadcast")
def broadcast(tensor, src=0, group=None, sync_op=True):
    """Inside shard_map all ranks trace identically; broadcast = take src's
    value. Implemented as psum of masked value (the XLA idiom)."""
    ax = _axis(group)
    v = unwrap(tensor)
    idx = jax.lax.axis_index(ax)
    masked = jnp.where(idx == src, v, jnp.zeros_like(v))
    out = jax.lax.psum(masked, ax)
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return out


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # all ranks get the reduction; non-dst ranks simply may ignore it
    return all_reduce(tensor, op=op, group=group)


@_collective("scatter")
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if tensor_list is not None:
        v = jnp.stack([unwrap(t) for t in tensor_list])
    else:
        v = unwrap(tensor)
    idx = jax.lax.axis_index(ax)
    src_val = broadcast(_wrap_value(v), src=src, group=group)
    out = unwrap(src_val)[idx]
    if isinstance(tensor, Tensor):
        tensor._value = out
        return tensor
    return out


@_collective("ppermute")
def ppermute(x, perm, group=None):
    """collective_permute (reference send_v2/recv_v2 pairs,
    operators/collective/send_v2_op.cu.cc:162)."""
    ax = _axis(group)
    return jax.lax.ppermute(unwrap(x), ax, perm)


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv have no single-program XLA analog; use "
        "ppermute (collective_permute) inside shard_map — see "
        "paddle_tpu.distributed.pipeline for the pipeline-parallel pattern"
    )


recv = send


@_collective("barrier")
def barrier(group=None):
    """No-op under a single controller: program order is the barrier."""
    return None


def get_group(name="dp"):
    class _Group:
        def __init__(self, axis_name):
            self.axis_name = axis_name

    return _Group(name)


def new_group(ranks=None, backend=None, timeout=None):
    """Parity shim (collective.py:343): groups are mesh axes on TPU."""
    return get_group("dp")


def wait(tensor, group=None, use_calc_stream=True):
    """Stream-sync parity (c_wait_comm): XLA schedules; block_until_ready for
    the eager-host case."""
    v = unwrap(tensor)
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()
    return tensor
