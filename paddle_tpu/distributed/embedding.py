"""Mesh-sharded embedding tables — the recsys workload's sparse tier.

Parity target: the reference's "100B-feature" recommender stack is a brpc
parameter server (SURVEY §3 PS/HeterPS) that the TPU port declares out of
scope; SURVEY §7 prescribes the replacement this module implements —
"sparse embeddings via sharded embedding tables on the mesh". The PS's
pull/push RPC pair becomes a pair of ``all_to_all`` collectives inside
``shard_map``:

- **lookup (pull)**: per shard, the local ids are deduplicated
  (``jnp.unique`` with a static size — the sorted output doubles as the
  PR-8 sort-based bucketing: unique ids arrive grouped by owner shard),
  bucketed by owner (= ``id // rows_per_shard``), exchanged with one
  ``all_to_all``, gathered from the owner's local ``[V/n, D]`` rows, and
  returned with a second ``all_to_all``; an inverse-permute gather puts
  rows back in request order. Payloads are O(batch), never O(vocab).
- **gradient (push)**: a ``custom_vjp`` routes the incoming ``[T, D]``
  cotangent back to the owner shards (token-level, stable-sorted by owner
  so every row's contributions arrive in global token order) and
  scatter-adds ONLY the touched local rows. No dense ``[V, D]`` gradient
  ever exists globally — each shard materializes just its own
  ``[V/n, D]`` cotangent block, and the bytes crossing the mesh are
  O(batch·D). This extends the SelectedRows contract
  (:mod:`paddle_tpu.framework.selected_rows`) into traced code; the
  matching traced row-sparse optimizer is
  :class:`paddle_tpu.optimizer.RowSparseAdam`.

The token-order accumulation discipline makes the sharded lookup AND its
gradient bitwise-identical to a single-device dense ``F.embedding``
reference (tests pin uniform, power-law-skewed, duplicate-id and
empty-shard batches on a dp4 CPU mesh).

Online learning (the PS's streaming role) is covered by
:class:`EmbeddingCheckpointRotation`: periodic row-sharded checkpoint
publication through :class:`~paddle_tpu.distributed.resilience.
CheckpointManager`, restorable onto a different mesh degree through the
PR-10 converter (dp4 -> dp2 -> dp4 round-trips bitwise).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer.base import Layer
from ..observability import runlog as _runlog
from ..observability.metrics import counter_inc as _counter_inc

__all__ = [
    "ShardedEmbedding", "sharded_embedding_lookup", "exchange_stats",
    "EmbeddingCheckpointRotation",
]


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@functools.lru_cache(maxsize=None)
def _local_lookup(n: int, axis: str, v_local: int, v_pad: int,
                  num_emb: int, cap: int):
    """The per-shard exchange body (ids ``[T]`` int32, table
    ``[v_local, D]``), built once per static signature. ``v_pad`` (the
    padded global row count) is the id sentinel: it is outside every
    shard's range, so padded exchange slots can never alias a real row."""

    @jax.custom_vjp
    def lookup(table, ids):
        out, _ = _fwd(table, ids)
        return out

    def _positions(owner_eff, T):
        # offset-from-run-start positions, the PR-8 dispatch shape: bucket
        # sizes via bincount, run starts via exclusive cumsum
        counts = jnp.bincount(owner_eff, length=n + 1)
        starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
        return jnp.arange(T, dtype=jnp.int32) - starts[owner_eff]

    def _fwd(table, ids):
        T = ids.shape[0]
        me = jax.lax.axis_index(axis)
        valid = (ids >= 0) & (ids < num_emb)
        ids_s = jnp.where(valid, ids, v_pad).astype(jnp.int32)
        # unique ids, statically sized; jnp.unique sorts, so the result is
        # already grouped by owner shard (owner = id // v_local ascends)
        uniq, inv = jnp.unique(ids_s, size=T, fill_value=v_pad,
                               return_inverse=True)
        uniq = uniq.astype(jnp.int32)
        inv = inv.reshape(T).astype(jnp.int32)
        u_valid = uniq < v_pad
        owner = jnp.clip(uniq // v_local, 0, n - 1).astype(jnp.int32)
        owner_eff = jnp.where(u_valid, owner, n).astype(jnp.int32)
        pos = _positions(owner_eff, T)
        keep = u_valid & (pos < cap)
        send = jnp.full((n, cap), v_pad, jnp.int32)
        send = send.at[jnp.where(keep, owner_eff, n),
                       jnp.where(keep, pos, 0)].set(uniq, mode="drop")
        # id exchange: row d of the result holds the ids shard d asks me for
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        lidx = jnp.clip(recv - me * v_local, 0, v_local - 1)
        gathered = table[lidx]  # [n, cap, D]; padded slots are never read back
        back = jax.lax.all_to_all(gathered, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        uniq_emb = back[jnp.clip(owner_eff, 0, n - 1),
                        jnp.clip(pos, 0, cap - 1)]
        out = uniq_emb[inv]
        tok_live = valid & keep[inv]  # out-of-range or capacity-dropped -> 0-row
        out = jnp.where(tok_live[:, None], out, 0.0).astype(table.dtype)
        return out, (ids_s, tok_live)

    def _bwd(res, dy):
        # Token-level (not unique-level) routing, stable-sorted by owner:
        # each shard holds a contiguous global-token range, so the owner's
        # flat (peer, slot) scatter order IS global token order — the same
        # per-row accumulation chain as the dense reference's single
        # scatter, hence bitwise-equal grads even for cross-shard
        # duplicate ids. Capacity is T here (never drops): every live
        # token's gradient must land.
        ids_s, tok_live = res
        T = ids_s.shape[0]
        me = jax.lax.axis_index(axis)
        owner = jnp.clip(ids_s // v_local, 0, n - 1).astype(jnp.int32)
        owner_eff = jnp.where(tok_live, owner, n).astype(jnp.int32)
        order = jnp.argsort(owner_eff, stable=True).astype(jnp.int32)
        oe_sorted = owner_eff[order]
        pos = _positions(owner_eff, T)[order]
        keep = oe_sorted < n
        dy_sorted = jnp.where(tok_live[order][:, None], dy[order], 0.0)
        row = jnp.where(keep, oe_sorted, n)
        col = jnp.where(keep, pos, 0)
        send_g = jnp.zeros((n, T) + dy.shape[1:], dy.dtype)
        send_g = send_g.at[row, col].set(dy_sorted, mode="drop")
        send_i = jnp.full((n, T), v_pad, jnp.int32)
        send_i = send_i.at[row, col].set(ids_s[order], mode="drop")
        g_recv = jax.lax.all_to_all(send_g, axis, split_axis=0,
                                    concat_axis=0, tiled=False)
        i_recv = jax.lax.all_to_all(send_i, axis, split_axis=0,
                                    concat_axis=0, tiled=False)
        flat_g = g_recv.reshape((n * T,) + dy.shape[1:])
        flat_i = i_recv.reshape(n * T)
        ok = flat_i < v_pad
        lidx = jnp.where(ok, flat_i - me * v_local, v_local)
        d_table = jnp.zeros((v_local,) + dy.shape[1:], dy.dtype)
        # the row-sparse push: one scatter-add into the touched local rows
        d_table = d_table.at[lidx].add(flat_g, mode="drop")
        d_ids = np.zeros(ids_s.shape, dtype=jax.dtypes.float0)
        return d_table, d_ids

    lookup.defvjp(_fwd, _bwd)
    return lookup


def sharded_embedding_lookup(ids, table, mesh, axis: str = "dp",
                             num_embeddings: Optional[int] = None,
                             capacity: Optional[int] = None):
    """Row-sharded embedding lookup over ``mesh[axis]`` inside shard_map.

    ``table`` is the global ``[V, D]`` array (placed ``P(axis)``); ``ids``
    is any-int-shaped with the leading (batch) dim sharded over ``axis``.
    ``num_embeddings`` bounds the valid id range (defaults to V); ids
    outside it return the zero row, the documented traced-mode contract
    shared with ``F.embedding``. ``capacity`` caps the per-destination
    unique-id exchange (a production knob for pathological skew);
    overflowing ids drop to the zero row — the default (per-shard token
    count) is exact. Returns ``ids.shape + (D,)``, batch-sharded.
    """
    from jax.sharding import PartitionSpec as P

    n = int(mesh.shape[axis])
    V, D = int(table.shape[0]), int(table.shape[1])
    if V % n != 0:
        raise ValueError(
            f"sharded_embedding_lookup: table rows {V} not divisible by "
            f"mesh axis {axis!r} degree {n}; pad the table (ShardedEmbedding "
            "pad_multiple handles this at construction)")
    batch = int(ids.shape[0])
    if batch % n != 0:
        raise ValueError(
            f"sharded_embedding_lookup: batch dim {batch} not divisible by "
            f"mesh axis {axis!r} degree {n}")
    t_local = int(np.prod(ids.shape)) // n
    cap = t_local if capacity is None else max(1, min(int(capacity), t_local))
    local = _local_lookup(n, axis, V // n, V,
                          int(num_embeddings or V), cap)

    def body(table_l, ids_l):
        out = local(table_l, ids_l.reshape(-1))
        return out.reshape(ids_l.shape + (D,))

    in_specs = (P(axis), P(*([axis] + [None] * (ids.ndim - 1))))
    out_specs = P(*([axis] + [None] * ids.ndim))
    return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(table, ids)


def exchange_stats(batch_tokens: int, vocab: int, dim: int, shards: int,
                   capacity: Optional[int] = None, itemsize: int = 4) -> dict:
    """Static per-step exchange accounting for one lookup over ``shards``
    devices: ids/embedding payload bytes for the forward pair of
    ``all_to_all``s and the backward pair, summed over devices (diagonal
    included). Shape-derived — no dispatch needed, which is what lets the
    bench and the run log report ``embedding_a2a_bytes_per_step`` without
    instrumenting the compiled program."""
    t_local = max(1, batch_tokens // max(1, shards))
    cap = t_local if capacity is None else max(1, min(int(capacity), t_local))
    ids_fwd = shards * shards * cap * 4
    emb_fwd = shards * shards * cap * dim * itemsize
    ids_bwd = shards * shards * t_local * 4
    emb_bwd = shards * shards * t_local * dim * itemsize
    return {
        "shards": shards, "ids": batch_tokens, "capacity": cap,
        "bytes_ids": ids_fwd + ids_bwd,
        "bytes_emb": emb_fwd + emb_bwd,
        "bytes_total": ids_fwd + emb_fwd + ids_bwd + emb_bwd,
        "vocab": vocab, "dim": dim,
    }


class ShardedEmbedding(Layer):
    """An embedding table row-sharded over a mesh axis.

    The ``[V, D]`` weight is annotated ``dist_spec = P(axis)`` (and
    ``_row_shard_axis``, the planner's template hint), so
    ``fleet.distributed_step`` / ``planner.build_step`` place it
    row-sharded; the forward routes lookups through
    :func:`sharded_embedding_lookup` when the active mesh carries the axis
    with degree > 1, and falls back to a dense local lookup (identical
    zero-row semantics) on a single device. The mesh is resolved at trace
    time from ``fleet``'s topology — the same hook the planner's candidate
    scope overrides — unless an explicit ``mesh`` is pinned.

    ``num_embeddings`` is the valid id range; the stored table is padded to
    a ``pad_multiple`` row count so every mesh degree up to the multiple
    divides it. In eager mode the layer records touched rows on the weight
    (the ``Embedding(sparse=True)`` SelectedRows contract) so eager lazy
    optimizers step only those rows.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axis: str = "dp", mesh=None, capacity: Optional[int] = None,
                 pad_multiple: int = 8, weight_attr=None, name=None):
        super().__init__()
        from jax.sharding import PartitionSpec as P

        from ..nn import initializer as I

        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.axis = axis
        self.capacity = capacity
        self._mesh = mesh
        self.padded_rows = _round_up(self.num_embeddings, max(1, pad_multiple))
        self.weight = self.create_parameter(
            [self.padded_rows, self.embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight.dist_spec = P(axis)
        self.weight._row_shard_axis = axis

    def _resolve_mesh(self):
        if self._mesh is not None:
            return self._mesh
        from .fleet import fleet

        return fleet.mesh

    def forward(self, x):
        from ..framework.autograd import is_grad_enabled
        from ..framework.selected_rows import is_traced_value, record_rows
        from ..tensor._helpers import ensure_tensor, op

        x = ensure_tensor(x)
        mesh = self._resolve_mesh()
        n = int(mesh.shape.get(self.axis, 1)) if mesh is not None else 1
        num_emb, v_pad = self.num_embeddings, self.padded_rows
        ids_val = x._value
        if is_grad_enabled() and not is_traced_value(ids_val) \
                and not self.weight.stop_gradient:
            # eager SelectedRows contract: note touched rows for lazy
            # optimizers, and account them (traced steps report through the
            # run-log exchange events instead)
            rows = np.unique(np.asarray(ids_val).ravel())
            record_rows(self.weight, rows)
            _counter_inc("embedding.rows_touched", int(rows.size))
        _counter_inc("embedding.lookups")
        if n > 1:
            stats = exchange_stats(
                int(np.prod(x.shape)), num_emb, self.embedding_dim, n,
                self.capacity, np.dtype(self.weight._value.dtype).itemsize)
            _counter_inc("embedding.ids_exchanged", stats["ids"])
            _counter_inc("embedding.a2a_bytes", stats["bytes_total"])
            _runlog.emit("embedding_exchange", axis=self.axis,
                         traced=bool(is_traced_value(ids_val)), **stats)
            cap = self.capacity

            def fn(w, idx):
                return sharded_embedding_lookup(
                    idx, w, mesh, axis=self.axis, num_embeddings=num_emb,
                    capacity=cap)

            return op(fn, self.weight, x, _name="sharded_embedding")

        def dense(w, idx):
            # single-shard fallback: same zero-row semantics as the
            # exchange path (and as traced F.embedding)
            bad = (idx < 0) | (idx >= num_emb)
            out = jnp.take(w, jnp.clip(idx, 0, v_pad - 1), axis=0)
            return jnp.where(bad[..., None], 0.0, out).astype(w.dtype)

        return op(dense, self.weight, x, _name="embedding_dense")

    def exchange_stats(self, batch_tokens: int, shards: Optional[int] = None) -> dict:
        """Static per-step a2a accounting for a ``batch_tokens``-id lookup
        (see module-level :func:`exchange_stats`)."""
        if shards is None:
            mesh = self._resolve_mesh()
            shards = int(mesh.shape.get(self.axis, 1)) if mesh is not None else 1
        return exchange_stats(batch_tokens, self.num_embeddings,
                              self.embedding_dim, shards, self.capacity,
                              np.dtype(self.weight._value.dtype).itemsize)

    def extra_repr(self):
        return (f"num_embeddings={self.num_embeddings} (padded "
                f"{self.padded_rows}), dim={self.embedding_dim}, "
                f"axis={self.axis!r}")


class EmbeddingCheckpointRotation:
    """Online-learning checkpoint hook: periodic row-sharded embedding
    checkpoint publication.

    The reference PS streams per-key updates to stand-by storage; here the
    sharded table already lives partitioned on the mesh, so the hook is
    rotation policy around :class:`~paddle_tpu.distributed.resilience.
    CheckpointManager`: every ``every`` optimizer steps the TrainStep state
    is published atomically (keep-last-k GC is the manager's), with
    ``embedding.rows_checkpointed`` accounting for the table leaves named
    in ``table_names``. Restores go through
    ``CheckpointManager.restore_latest(target=..., shardings=...)`` — the
    PR-10 converter reshards row partitions bitwise across mesh degrees,
    so an elastic rescale (dp4 -> dp2) resumes on a re-partitioned table.
    """

    def __init__(self, manager, every: int = 100, table_names=()):
        if int(every) < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self.manager = manager
        self.every = int(every)
        self.table_names = tuple(table_names)
        self._last_saved: Optional[int] = None

    def maybe_save(self, state, step: int) -> Optional[str]:
        """Publish ``state`` when ``step`` crosses the rotation period;
        returns the checkpoint path or None when not due."""
        if self._last_saved is not None and step - self._last_saved < self.every:
            return None
        return self.save(state, step)

    def save(self, state, step: int) -> str:
        from ..stability import state_to_savable

        path = self.manager.save(state_to_savable(state), int(step))
        params = state.get("params", {}) if isinstance(state, dict) else {}
        rows = sum(int(params[name].shape[0]) for name in self.table_names
                   if name in params)
        if rows:
            _counter_inc("embedding.rows_checkpointed", rows)
        self._last_saved = int(step)
        return path

    def restore(self, target=None, shardings=None):
        """(state, step) from the newest valid checkpoint, converted onto
        ``target``/``shardings`` (a different mesh degree reshards the row
        partition bitwise); None when no checkpoint exists. ``target`` is a
        *savable* tree (``stability.state_to_savable``); the returned state
        is already mapped back through ``state_from_savable``."""
        from ..stability import state_from_savable

        got = self.manager.restore_latest(target=target, shardings=shardings)
        if got is None:
            return None
        state, step = got
        return state_from_savable(state), step
