"""paddle.distributed.utils parity (reference distributed/utils.py):
cluster/pod/trainer descriptors and launcher helpers, plus the MoE
global_scatter/global_gather collectives.

The descriptors are what the reference launcher builds from env vars; here
they wrap the same facts for the TCPStore-based launcher in launch/main.py.
"""
from __future__ import annotations

import logging
import os
import signal
import socket
from typing import List, Optional

__all__ = [
    "get_host_name_ip", "Trainer", "get_cluster", "start_local_trainers",
    "watch_local_trainers", "find_free_ports", "JobServer", "Cluster", "Pod",
    "Hdfs", "add_arguments", "terminate_local_procs", "TrainerProc",
    "get_logger", "pull_worker_log", "global_scatter", "global_gather",
]


def get_host_name_ip():
    try:
        name = socket.gethostname()
        return name, socket.gethostbyname(name)
    except OSError:
        return None, None


def find_free_ports(num: int) -> Optional[set]:
    out = set()
    socks = []
    try:
        for _ in range(num):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind(("", 0))
            socks.append(s)
            out.add(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return out


def get_logger(log_level=20, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("%(levelname)s %(asctime)s %(message)s"))
        logger.addHandler(h)
    return logger


class Trainer:
    def __init__(self):
        self.gpus: List[int] = []
        self.endpoint: Optional[str] = None
        self.rank: Optional[int] = None

    def __str__(self):
        return f"gpus:{self.gpus} endpoint:{self.endpoint} rank:{self.rank}"

    def __eq__(self, other):
        return (self.gpus, self.endpoint, self.rank) == (other.gpus, other.endpoint, other.rank)

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)


class JobServer:
    def __init__(self):
        self.endpoint: Optional[str] = None

    def __str__(self):
        return str(self.endpoint)

    def __eq__(self, other):
        return self.endpoint == other.endpoint

    def __ne__(self, other):
        return not self == other


class Pod:
    def __init__(self):
        self.rank: Optional[int] = None
        self.id: Optional[str] = None
        self.addr: Optional[str] = None
        self.port: Optional[int] = None
        self.trainers: List[Trainer] = []
        self.gpus: List[int] = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} port:{self.port} "
                f"trainers:{[str(t) for t in self.trainers]}")

    def __eq__(self, other):
        return (self.rank, self.id, self.addr, self.port) == \
            (other.rank, other.id, other.addr, other.port) and self.trainers == other.trainers

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)

    def get_visible_gpus(self):
        return ",".join(str(g) for g in self.gpus)


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return bool(self.hdfs_ugi and self.hdfs_name and self.hdfs_path)

    def __str__(self):
        return f"hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} hdfs_path:{self.hdfs_path}"

    def __eq__(self, other):
        return str(self) == str(other)

    def __ne__(self, other):
        return not self == other


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server: Optional[JobServer] = None
        self.pods: List[Pod] = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return f"job_server:{self.job_server} pods:{[str(p) for p in self.pods]}"

    def __eq__(self, other):
        return len(self.pods) == len(other.pods) and all(
            a == b for a, b in zip(self.pods, other.pods))

    def __ne__(self, other):
        return not self == other

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def pods_nranks(self):
        return len(self.pods)

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def get_pod_by_id(self, pod_id):
        for p in self.pods:
            if str(pod_id) == str(p.id):
                return p
        return None


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def get_cluster(node_ips, node_ip, trainer_endpoints, device_mode_or_gpus, devices_per_proc=None):
    """Build a Cluster/Pod description (reference utils.get_cluster): one pod
    per node ip, one trainer per endpoint on that node."""
    if devices_per_proc is None:
        devices_per_proc = device_mode_or_gpus  # legacy positional form
    cluster = Cluster()
    rank = 0
    nested = bool(trainer_endpoints) and isinstance(trainer_endpoints[0], (list, tuple))
    trainer_endpoints = trainer_endpoints or []
    # flat list: endpoints are split evenly across nodes in order
    per_node = len(trainer_endpoints) // max(len(node_ips), 1) if not nested else 0
    if not nested and trainer_endpoints and (
            per_node == 0 or len(trainer_endpoints) % max(len(node_ips), 1) != 0):
        raise ValueError(f"{len(trainer_endpoints)} endpoints cannot be split "
                         f"evenly over {len(node_ips)} nodes; pass a nested "
                         f"per-node endpoint list for uneven layouts")
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        if nested:
            eps = trainer_endpoints[node_rank]
        else:
            eps = trainer_endpoints[node_rank * per_node:(node_rank + 1) * per_node]
        for i, ep in enumerate(eps):
            t = Trainer()
            t.endpoint = ep
            t.rank = rank
            t.gpus = [devices_per_proc[i]] if isinstance(devices_per_proc, (list, tuple)) \
                and i < len(devices_per_proc) else []
            rank += 1
            pod.trainers.append(t)
        cluster.pods.append(pod)
    pod = cluster.pods[node_ips.index(node_ip)] if node_ip in node_ips else cluster.pods[0]
    return cluster, pod


def start_local_trainers(cluster, pod, training_script, training_script_args,
                         log_dir=None, envs=None):
    """Spawn one subprocess per trainer of this pod (reference
    start_local_trainers) with the PADDLE_* env contract."""
    import subprocess
    import sys

    procs = []
    for idx, t in enumerate(pod.trainers):
        env = dict(os.environ, **(envs or {}))
        env.update({
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_CURRENT_ENDPOINT": str(t.endpoint),
            "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(cluster.trainers_endpoints()),
        })
        cmd = [sys.executable, "-u", training_script] + list(training_script_args)
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            fn = open(os.path.join(log_dir, f"workerlog.{idx}"), "a")
            proc = subprocess.Popen(cmd, env=env, stdout=fn, stderr=fn)
        else:
            fn = None
            proc = subprocess.Popen(cmd, env=env)
        tp = TrainerProc()
        tp.proc, tp.rank, tp.local_rank, tp.log_fn, tp.cmd = proc, t.rank, idx, fn, cmd
        procs.append(tp)
    return procs


def watch_local_trainers(procs, nranks):
    """Poll trainer procs; raise on failure, prune (and close logs of)
    cleanly exited ones (reference watch_local_trainers)."""
    alive = []
    for p in procs:
        ret = p.proc.poll()
        if ret is None:
            alive.append(p)
        else:
            if p.log_fn:
                p.log_fn.close()
            if ret != 0:
                raise RuntimeError(f"trainer rank {p.rank} failed with exit code {ret}")
    return alive


def terminate_local_procs(procs):
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            try:
                p.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
    for p in procs:
        if p.proc is not None:
            try:
                p.proc.wait(timeout=10)
            except Exception:
                p.proc.kill()
        if p.log_fn:
            p.log_fn.close()


def pull_worker_log(tp: TrainerProc):
    if not tp.log_fn:
        return
    with open(tp.log_fn.name, "rb") as fin:
        fin.seek(tp.log_offset or 0, 0)
        for line in fin:
            try:
                print(line.decode("utf-8", errors="replace"), end="")
            except OSError:
                break
        tp.log_offset = fin.tell()


def add_arguments(argname, type, default, help, argparser, **kwargs):  # noqa: A002
    """argparse helper (reference utils.add_arguments)."""
    argparser.add_argument("--" + argname, default=default, type=type,
                           help=f"{help} Default: %(default)s.", **kwargs)


def _global_exchange(x, local_count, global_count, gather):
    """Count-aware exchange (reference global_scatter/global_gather ops).
    Under the single-controller SPMD model there is no per-rank send/recv:
    the multi-device dispatch compiles to XLA all_to_all inside MoELayer.
    These functions implement the reference's data contract for the
    single-process layout (counts validate, data passes through in expert
    order); a multi-process group is directed to MoELayer."""
    import numpy as np

    from ..tensor._helpers import ensure_tensor, unwrap

    xt = ensure_tensor(x)
    lc = np.asarray(unwrap(ensure_tensor(local_count))).ravel()
    gc = np.asarray(unwrap(ensure_tensor(global_count))).ravel()
    n = xt.shape[0]
    send = int(lc.sum())
    recv = int(gc.sum())
    if (gather and n != recv) or (not gather and n != send):
        raise ValueError(f"count mismatch: rows={n}, local={send}, global={recv}")
    if not np.array_equal(lc, gc):
        raise NotImplementedError(
            "cross-rank global_scatter/global_gather: use distributed.MoELayer "
            "— expert dispatch compiles to XLA all_to_all over the mesh")
    return xt


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    return _global_exchange(x, local_count, global_count, gather=False)


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    return _global_exchange(x, local_count, global_count, gather=True)
