"""Process/device environment (parity: python/paddle/distributed/parallel.py
env reading + paddle.distributed.launch).

TPU-first: one process per *host*, all devices visible to JAX;
``init_parallel_env`` maps to ``jax.distributed.initialize`` (DCN rendezvous
— the TCPStore/gen_comm_id analog, reference
paddle/fluid/distributed/store/tcp_store.h:97) and "rank" means process
(host) index, while device-level parallelism is mesh axes, not processes.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def init_parallel_env(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host init. Single-host (the common case for tests/one-chip) is a
    no-op: every device is already visible."""
    global _initialized
    if _initialized:
        return
    addr = coordinator_address or os.environ.get("PADDLE_MASTER") or os.environ.get("COORDINATOR_ADDRESS")
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM", "0")) or None
    pid = process_id if process_id is not None else int(os.environ.get("PADDLE_TRAINER_ID", "-1"))
    if addr and nproc and nproc > 1:
        # TCPStore rendezvous before the XLA coordinator comes up (reference
        # parallel.py:267-333 barriers on the store before comm init): rank 0
        # hosts the store one port above the coordinator, all ranks barrier so
        # late workers don't race jax.distributed.initialize.
        store = None
        if pid >= 0:  # with an unknown rank nobody can host; skip the store
            try:
                from .store import TCPStore

                host, port = addr.rsplit(":", 1)
                store = TCPStore(host, int(port) + 1, is_master=(pid == 0),
                                 world_size=nproc, timeout=30.0)
                store.barrier("init_parallel_env", timeout=30.0)
            except Exception as e:
                import logging

                logging.getLogger(__name__).warning(
                    "TCPStore rendezvous skipped (%s); relying on the "
                    "coordinator's own blocking rendezvous", e)
                store = None
        jax.distributed.initialize(coordinator_address=addr, num_processes=nproc, process_id=pid if pid >= 0 else None)
        if store is not None:
            store.close()
    _initialized = True


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_device_count() -> int:
    return jax.local_device_count()


def get_device_count() -> int:
    return jax.device_count()


class ParallelEnv:
    """Parity shim for paddle.distributed.ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
