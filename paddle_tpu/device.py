"""Device memory observability (parity: paddle.device.cuda
max_memory_allocated/max_memory_reserved/memory_allocated/memory_reserved,
backed by memory/stats.h DEVICE_MEMORY_STAT_* in the reference).

TPU-first: numbers come straight from PJRT's per-device allocator
(``Device.memory_stats()``), so they are live HBM figures, not a shadow
counter. All APIs accept a device ordinal / "tpu:N" string / None (current
device).
"""
from __future__ import annotations

from typing import Optional

import jax


def _device(device=None):
    devs = jax.devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str):
        idx = int(device.split(":")[1]) if ":" in device else 0
        return devs[idx]
    return device  # already a jax Device


_PEAK_FALLBACK: dict = {}  # device id -> watermark for the live-buffer fallback


def _live_buffer_bytes(d) -> int:
    """Sum of live jax.Array bytes resident on ``d`` — the fallback
    accounting when PJRT does not forward allocator stats (e.g. through the
    axon tunnel or on CPU)."""
    total = 0
    for arr in jax.live_arrays():
        try:
            if any(dev == d for dev in arr.devices()):
                total += arr.nbytes // max(len(arr.devices()), 1)
        except Exception:
            continue
    return total


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats (bytes_in_use, peak_bytes_in_use,
    bytes_limit, largest_alloc_size, ...). When the backend exposes none
    (CPU, tunneled TPU), falls back to live-buffer accounting with a
    process-local peak watermark."""
    d = _device(device)
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    if stats:
        return dict(stats)
    in_use = _live_buffer_bytes(d)
    peak = max(_PEAK_FALLBACK.get(d.id, 0), in_use)
    _PEAK_FALLBACK[d.id] = peak
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak, "bytes_limit": 0, "source": "live_arrays"}


def memory_allocated(device=None) -> int:
    """Live HBM bytes currently allocated on the device."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak HBM bytes allocated since device initialization."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool (== in_use under PJRT's BFC
    accounting when no pool stat is exposed)."""
    s = memory_stats(device)
    return int(s.get("pool_bytes", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def device_memory_limit(device=None) -> int:
    """Total usable HBM on the device (bytes_limit)."""
    return int(memory_stats(device).get("bytes_limit", 0))


def empty_cache():
    """Parity no-op: PJRT owns the HBM pool; there is no user-drainable
    cache. Kept so monitoring code ports cleanly."""
    return None


def device_count() -> int:
    return jax.device_count()


def get_device_properties(device=None):
    d = _device(device)
    return {
        "name": getattr(d, "device_kind", d.platform),
        "platform": d.platform,
        "id": d.id,
        "process_index": d.process_index,
        "total_memory": device_memory_limit(d),
    }


# -- device-query surface (reference python/paddle/device/__init__.py) -------
# On this framework the only accelerator is the TPU via PJRT; the CUDA/XPU/
# NPU/MLU/IPU predicates exist for source compatibility and answer False.

from .framework.core import get_device, set_device  # noqa: E402,F401
from .framework.param_attr import (  # noqa: E402,F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    NPUPlace,
    TPUPlace,
)


class XPUPlace(TPUPlace):
    pass


class MLUPlace(TPUPlace):
    pass


class IPUPlace(TPUPlace):
    pass


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA is the tensor compiler here; the CINN-specific toggle is False
    return False


def get_cudnn_version():
    return None  # no cuDNN on TPU


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "gpu", "tpu")]


def get_available_device():
    import jax

    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device()
            if d.split(":")[0] not in ("cpu", "gpu", "tpu")]


class _CudaNamespace:
    """paddle.device.cuda compatibility: memory queries map to the PJRT
    allocator stats above (reference device/cuda/__init__.py)."""

    max_memory_allocated = staticmethod(max_memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_allocated = staticmethod(memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    empty_cache = staticmethod(empty_cache)
    device_count = staticmethod(device_count)
    get_device_properties = staticmethod(get_device_properties)

    @staticmethod
    def synchronize(device=None):
        import jax

        jax.block_until_ready(jax.numpy.zeros(()))


cuda = _CudaNamespace()
