"""Device memory observability (parity: paddle.device.cuda
max_memory_allocated/max_memory_reserved/memory_allocated/memory_reserved,
backed by memory/stats.h DEVICE_MEMORY_STAT_* in the reference).

TPU-first: numbers come straight from PJRT's per-device allocator
(``Device.memory_stats()``), so they are live HBM figures, not a shadow
counter. All APIs accept a device ordinal / "tpu:N" string / None (current
device).
"""
from __future__ import annotations

from typing import Optional

import jax


def _device(device=None):
    devs = jax.devices()
    if device is None:
        return devs[0]
    if isinstance(device, int):
        return devs[device]
    if isinstance(device, str):
        idx = int(device.split(":")[1]) if ":" in device else 0
        return devs[idx]
    return device  # already a jax Device


_PEAK_FALLBACK: dict = {}  # device id -> watermark for the live-buffer fallback


def _live_buffer_bytes(d) -> int:
    """Sum of live jax.Array bytes resident on ``d`` — the fallback
    accounting when PJRT does not forward allocator stats (e.g. through the
    axon tunnel or on CPU)."""
    total = 0
    for arr in jax.live_arrays():
        try:
            if any(dev == d for dev in arr.devices()):
                total += arr.nbytes // max(len(arr.devices()), 1)
        except Exception:
            continue
    return total


def memory_stats(device=None) -> dict:
    """Raw PJRT allocator stats (bytes_in_use, peak_bytes_in_use,
    bytes_limit, largest_alloc_size, ...). When the backend exposes none
    (CPU, tunneled TPU), falls back to live-buffer accounting with a
    process-local peak watermark."""
    d = _device(device)
    try:
        stats = d.memory_stats()
    except Exception:
        stats = None
    if stats:
        return dict(stats)
    in_use = _live_buffer_bytes(d)
    peak = max(_PEAK_FALLBACK.get(d.id, 0), in_use)
    _PEAK_FALLBACK[d.id] = peak
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak, "bytes_limit": 0, "source": "live_arrays"}


def memory_allocated(device=None) -> int:
    """Live HBM bytes currently allocated on the device."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    """Peak HBM bytes allocated since device initialization."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    """Bytes reserved by the allocator pool (== in_use under PJRT's BFC
    accounting when no pool stat is exposed)."""
    s = memory_stats(device)
    return int(s.get("pool_bytes", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_pool_bytes", s.get("peak_bytes_in_use", 0)))


def device_memory_limit(device=None) -> int:
    """Total usable HBM on the device (bytes_limit)."""
    return int(memory_stats(device).get("bytes_limit", 0))


def empty_cache():
    """Parity no-op: PJRT owns the HBM pool; there is no user-drainable
    cache. Kept so monitoring code ports cleanly."""
    return None


def device_count() -> int:
    return jax.device_count()


def get_device_properties(device=None):
    d = _device(device)
    return {
        "name": getattr(d, "device_kind", d.platform),
        "platform": d.platform,
        "id": d.id,
        "process_index": d.process_index,
        "total_memory": device_memory_limit(d),
    }
