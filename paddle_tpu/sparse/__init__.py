"""paddle_tpu.sparse — COO/CSR sparse tensors.

Parity: ``paddle.sparse``/``paddle.incubate.sparse`` (reference
paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h + kernels under
paddle/phi/kernels/sparse/). TPU-first: backed by jax.experimental.sparse
BCOO/BCSR, whose ops lower to XLA gather/scatter — dense-compute-with-mask is
usually faster on the MXU, so to_dense() is the recommended hot-path escape.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, _wrap_value, unwrap

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor", "sparse_csr_tensor",
    "add", "subtract", "multiply", "matmul", "relu", "sum", "transpose", "nn",
]


class SparseCooTensor:
    """COO sparse tensor (reference sparse_coo_tensor.h): indices [ndim, nnz]
    + values [nnz, ...]."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._m = bcoo

    # -- reference API ----------------------------------------------------
    def indices(self) -> Tensor:
        return _wrap_value(self._m.indices.T)  # paddle layout [ndim, nnz]

    def values(self) -> Tensor:
        return _wrap_value(self._m.data)

    def to_dense(self) -> Tensor:
        return _wrap_value(self._m.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        dense = self._m.todense()
        if dense.ndim != 2:
            raise ValueError("to_sparse_csr requires a 2-D tensor")
        return SparseCsrTensor(jsparse.BCSR.fromdense(dense))

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._m.sum_duplicates())

    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def nnz(self) -> int:
        return int(self._m.nse)

    @property
    def dtype(self):
        return self._m.dtype

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"


class SparseCsrTensor:
    """CSR sparse tensor (reference sparse_csr_tensor.h)."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._m = bcsr

    def crows(self) -> Tensor:
        return _wrap_value(self._m.indptr)

    def cols(self) -> Tensor:
        return _wrap_value(self._m.indices)

    def values(self) -> Tensor:
        return _wrap_value(self._m.data)

    def to_dense(self) -> Tensor:
        return _wrap_value(self._m.todense())

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(jsparse.BCOO.fromdense(self._m.todense()))

    @property
    def shape(self):
        return list(self._m.shape)

    @property
    def nnz(self) -> int:
        return int(self._m.nse)

    @property
    def dtype(self):
        return self._m.dtype

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, dtype={self.dtype})"


def sparse_coo_tensor(indices, values, shape: Sequence[int] = None, dtype=None, place=None, stop_gradient=True):
    """Build COO from paddle-layout indices [ndim, nnz] + values [nnz]."""
    idx = jnp.asarray(unwrap(indices) if isinstance(indices, Tensor) else indices)
    val = jnp.asarray(unwrap(values) if isinstance(values, Tensor) else values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype

        val = val.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(i) for i in (idx.max(axis=1) + 1))
    return SparseCooTensor(jsparse.BCOO((val, idx.T.astype(jnp.int32)), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape: Sequence[int], dtype=None, place=None, stop_gradient=True):
    cr = jnp.asarray(unwrap(crows) if isinstance(crows, Tensor) else crows, jnp.int32)
    cc = jnp.asarray(unwrap(cols) if isinstance(cols, Tensor) else cols, jnp.int32)
    val = jnp.asarray(unwrap(values) if isinstance(values, Tensor) else values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype

        val = val.astype(to_jax_dtype(dtype))
    return SparseCsrTensor(jsparse.BCSR((val, cc, cr), shape=tuple(shape)))


def _mat(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return x._m
    return unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)


def _rewrap(template, m):
    if isinstance(m, jsparse.BCOO):
        return SparseCooTensor(m)
    if isinstance(m, jsparse.BCSR):
        return SparseCsrTensor(m)
    return _wrap_value(m)


def add(x, y):
    a, b = _mat(x), _mat(y)
    if isinstance(a, jsparse.BCOO) and isinstance(b, jsparse.BCOO):
        return SparseCooTensor((a + b).sum_duplicates())
    return _rewrap(x, a.todense() + b if hasattr(a, "todense") else a + b.todense())


def subtract(x, y):
    a, b = _mat(x), _mat(y)
    if isinstance(a, jsparse.BCOO) and isinstance(b, jsparse.BCOO):
        return SparseCooTensor((a + (-b)).sum_duplicates())
    return _rewrap(x, a.todense() - b if hasattr(a, "todense") else a - b.todense())


def multiply(x, y):
    a, b = _mat(x), _mat(y)
    da = a.todense() if hasattr(a, "todense") else a
    db = b.todense() if hasattr(b, "todense") else b
    out = da * db
    if isinstance(x, SparseCooTensor) or isinstance(y, SparseCooTensor):
        return SparseCooTensor(jsparse.BCOO.fromdense(out))
    return _wrap_value(out)


def matmul(x, y):
    """sparse @ dense (reference sparse matmul kernels). Differentiable
    w.r.t. the dense operand: routed through primitive with the sparse
    structure closed over (constant)."""
    from ..tensor._helpers import ensure_tensor, op

    a = _mat(x)
    if hasattr(a, "todense") and isinstance(y, (Tensor, jnp.ndarray)) or isinstance(y, Tensor):
        m = a

        def fn(w):
            out = m @ w
            return out.todense() if hasattr(out, "todense") else out

        return op(fn, ensure_tensor(y), _name="sparse_matmul")
    b = _mat(y)
    out = a @ b
    return _wrap_value(out.todense() if hasattr(out, "todense") else out)


def masked_matmul(x, y, mask):
    """dense @ dense evaluated only at mask's nonzeros (reference
    masked_matmul): returns sparse with mask's sparsity."""
    a, b = _mat(x), _mat(y)
    m = mask._m if isinstance(mask, SparseCooTensor) else jsparse.BCOO.fromdense(_mat(mask))
    rows, cols = m.indices[:, 0], m.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=a.shape[:1] + b.shape[1:]))


def relu(x):
    if isinstance(x, SparseCooTensor):
        m = x._m
        return SparseCooTensor(jsparse.BCOO((jnp.maximum(m.data, 0), m.indices), shape=m.shape))
    if isinstance(x, SparseCsrTensor):
        m = x._m
        return SparseCsrTensor(jsparse.BCSR((jnp.maximum(m.data, 0), m.indices, m.indptr), shape=m.shape))
    return _wrap_value(jnp.maximum(_mat(x), 0))


def sum(x, axis=None, keepdim=False):
    d = _mat(x)
    d = d.todense() if hasattr(d, "todense") else d
    return _wrap_value(jnp.sum(d, axis=axis, keepdims=keepdim))


def transpose(x, perm):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x._m.transpose(tuple(perm)))
    return _wrap_value(jnp.transpose(_mat(x), perm))


class _SparseNN:
    class ReLU:
        def __call__(self, x):
            return relu(x)

    def __init__(self):
        self.ReLU = _SparseNN.ReLU


nn = _SparseNN()
