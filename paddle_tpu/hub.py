"""paddle.hub parity (reference python/paddle/hub.py): list/help/load models
from a hubconf.py. Zero-egress environment: the 'local' source is fully
supported; github/gitee sources raise with a clear message.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]


_hubconf_cache: dict = {}


def _load_hubconf(repo_dir, force_reload=False):
    path = os.path.abspath(os.path.join(repo_dir, "hubconf.py"))
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir}")
    if not force_reload and path in _hubconf_cache:
        return _hubconf_cache[path]
    key = f"paddle_tpu_hubconf_{abs(hash(path)):x}"  # per-repo module identity
    spec = importlib.util.spec_from_file_location(key, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[key] = mod
    spec.loader.exec_module(mod)
    _hubconf_cache[path] = mod
    return mod


def _check_source(source):
    if source != "local":
        raise NotImplementedError(
            f"hub source {source!r} needs network access (none here); "
            f"clone the repo and use source='local'")


def list(repo_dir, source="github", force_reload=False):  # noqa: A001 — reference name
    _check_source(source)
    mod = _load_hubconf(repo_dir, force_reload)
    return [n for n in dir(mod) if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    _check_source(source)
    return getattr(_load_hubconf(repo_dir, force_reload), model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir, force_reload), model)(**kwargs)
