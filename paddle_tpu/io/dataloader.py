"""DataLoader (parity: python/paddle/fluid/reader.py:275 + dataloader_iter.py).

TPU-first: the loader produces host numpy batches on background threads and
(optionally) prefetches the next batch to device while the current step runs —
replacing the reference's multiprocess worker + shared-memory LoDTensor
machinery (dataloader_iter.py:342) with a thread pool, since the heavy lifting
(decode/augment) releases the GIL in numpy and device transfer is async under
PJRT anyway.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..observability import span as _span
from ..observability.metrics import counter_inc as _counter_inc
from .dataset import BatchSampler, Dataset, IterableDataset


def stack_batches(it, k, to_device=True):
    """Group every ``k`` consecutive batches from ``it`` into one
    ``[k, ...]``-stacked pytree — the feed unit of the single-dispatch
    multi-step path (``TrainStep.run_steps`` / ``MultiStepRunner``).

    Stacking happens on host (numpy); with ``to_device`` each stack's
    host→HBM transfer is issued asynchronously one stack ahead (device_put
    is async under PJRT), preserving the loader's one-ahead overlap at stack
    granularity. A trailing group shorter than ``k`` is still yielded (its
    different leading dim costs one extra compile downstream).
    """
    import jax

    k = int(k)
    if k < 1:
        raise ValueError(f"stack_batches needs k >= 1, got {k}")

    def sig(batch):
        return tuple(np.shape(l) for l in jax.tree_util.tree_leaves(batch))

    def stacks():
        group = []
        for batch in it:
            # a ragged batch (e.g. a drop_last=False remainder) cannot join
            # the current stack: flush what we have, start a new group
            if group and sig(batch) != sig(group[0]):
                yield jax.tree_util.tree_map(lambda *xs: np.stack(xs), *group)
                group = []
            group.append(batch)
            if len(group) == k:
                yield jax.tree_util.tree_map(lambda *xs: np.stack(xs), *group)
                group = []
        if group:
            yield jax.tree_util.tree_map(lambda *xs: np.stack(xs), *group)

    if not to_device:
        yield from stacks()
        return

    def put(b):
        # async under PJRT: the span times the host-side issue, the transfer
        # itself overlaps the in-flight step
        with _span("dataloader.device_put"):
            _counter_inc("dataloader.device_puts")
            return jax.tree_util.tree_map(jax.device_put, b)

    prev = None
    for stack in stacks():
        nxt = put(stack)
        if prev is not None:
            yield prev
        prev = nxt
    if prev is not None:
        yield prev


# sentinel flowing through the worker/iterator plumbing in place of a batch
# whose sample/collate raised (FLAGS_dataloader_max_bad_batches > 0); the
# consumer-facing iterator filters it out
_SKIPPED_BATCH = object()


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    # Tensor / jax array
    if hasattr(sample, "numpy"):
        return np.stack([np.asarray(b.numpy()) for b in batch])
    return np.stack([np.asarray(b) for b in batch])


class DataLoader:
    """num_workers>0 runs workers as THREADS by default (numpy/PIL release
    the GIL, and threads avoid fork/pickle constraints); pass
    ``worker_mode="process"`` for fork-based worker PROCESSES with
    shared-memory transport — the reference's multiprocess architecture
    (dataloader_iter.py:342) — for GIL-bound (pure-Python) augmentation
    pipelines. ``persistent_workers``/``timeout``/``worker_init_fn`` apply
    to process mode."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True, batch_sampler=None, batch_size=1, shuffle=False, drop_last=False, collate_fn=None, num_workers=0, use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True, timeout=0, worker_init_fn=None, persistent_workers=False, worker_mode="thread", fuse_steps=None):
        self.dataset = dataset
        # fuse_steps=K: yield [K, ...]-stacked device-resident batch stacks
        # (one per K steps) for TrainStep.run_steps instead of single batches
        self.fuse_steps = int(fuse_steps) if fuse_steps else None
        if self.fuse_steps is not None and self.fuse_steps < 1:
            raise ValueError(f"fuse_steps must be >= 1, got {fuse_steps}")
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        if worker_mode not in ("thread", "process"):
            raise ValueError(f"worker_mode must be 'thread' or 'process', got {worker_mode!r}")
        self.worker_mode = worker_mode
        self._pool = None  # persistent WorkerPool (process mode)
        self._bad_count = 0  # skipped batches this iteration (poison samples)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
            if worker_mode == "process":
                raise ValueError("worker_mode='process' needs a map-style "
                                 "dataset (IterableDataset iterates in-order "
                                 "in the main process; use threads)")
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        try:
            with _span("dataloader.fetch"):
                batch = self.collate_fn([self.dataset[i] for i in indices])
        except Exception as exc:
            return self._bad_batch(exc, indices=list(indices))
        _counter_inc("dataloader.batches")
        return batch

    def _bad_batch(self, exc, **info):
        """Poison-sample resilience (FLAGS_dataloader_max_bad_batches > 0):
        a sample/collate exception yields a skip sentinel — bounded per
        iteration — instead of killing the iterator mid-epoch."""
        from ..framework.flags import flag
        from ..observability import runlog

        limit = int(flag("FLAGS_dataloader_max_bad_batches"))
        if limit <= 0:
            raise exc
        self._bad_count += 1
        _counter_inc("dataloader.bad_batches")
        runlog.emit("bad_batch", count=self._bad_count, limit=limit,
                    error=f"{type(exc).__name__}: {exc}", **info)
        if self._bad_count > limit:
            raise RuntimeError(
                f"DataLoader: {self._bad_count} bad batches in one iteration "
                f"exceeds FLAGS_dataloader_max_bad_batches={limit}") from exc
        return _SKIPPED_BATCH

    def __iter__(self):
        self._bad_count = 0  # bad-batch budget is per iteration
        if self._iterable_mode:
            it = self._iter_iterable()
        elif self.num_workers == 0:
            it = (self._fetch(indices) for indices in self.batch_sampler)
        elif self.worker_mode == "process":
            it = self._iter_multiprocess()
        else:
            it = self._iter_threaded()
        it = (b for b in it if b is not _SKIPPED_BATCH)
        if self.fuse_steps is not None:
            # stack granularity subsumes per-batch prefetch: one async
            # device_put per K batches, still one stack ahead
            it = stack_batches(it, self.fuse_steps, to_device=self._prefetch_to_device())
        elif self._prefetch_to_device():
            it = self._iter_device_prefetch(it)
        yield from it

    def _iter_multiprocess(self):
        from .mp_worker import WorkerPool

        pool = self._pool
        if pool is None or pool._closed:
            pool = WorkerPool(self.dataset, self.collate_fn, self.num_workers,
                              worker_init_fn=self.worker_init_fn,
                              use_shm=self.use_shared_memory,
                              timeout=self.timeout,
                              prefetch_factor=self.prefetch_factor)
        if self.persistent_workers:
            self._pool = pool
            try:
                yield from pool.run_epoch(self.batch_sampler)
            except Exception:
                self._pool = None  # pool is shut down: respawn next epoch
                raise
        else:
            try:
                yield from pool.run_epoch(self.batch_sampler)
            finally:
                pool.shutdown()

    def shutdown(self):
        """Stop persistent process workers (no-op otherwise)."""
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass

    def _prefetch_to_device(self):
        """use_buffer_reader parity (reader.py:275): feed batches to the
        accelerator asynchronously, one batch ahead."""
        if not self.use_buffer_reader:
            return False
        import jax

        return jax.default_backend() != "cpu"

    def _iter_device_prefetch(self, it):
        """Yield batch N while batch N+1's host→HBM transfer is in flight
        (device_put is async under PJRT)."""
        import jax

        def put(b):
            with _span("dataloader.device_put"):
                _counter_inc("dataloader.device_puts")
                return jax.tree_util.tree_map(jax.device_put, b)

        prev = None
        for batch in it:
            nxt = put(batch)
            if prev is not None:
                yield prev
            prev = nxt
        if prev is not None:
            yield prev

    def _iter_iterable(self):
        def collate(b):
            try:
                with _span("dataloader.fetch"):
                    out = self.collate_fn(b)
            except Exception as exc:
                return self._bad_batch(exc)
            _counter_inc("dataloader.batches")
            return out

        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield collate(batch)
                batch = []
        if batch and not self.drop_last:
            yield collate(batch)

    def _iter_threaded(self):
        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            pending = queue.Queue()
            sampler_iter = iter(self.batch_sampler)
            in_flight = 0
            limit = self.num_workers * self.prefetch_factor

            def submit_next():
                nonlocal in_flight
                try:
                    indices = next(sampler_iter)
                except StopIteration:
                    return False
                pending.put(pool.submit(self._fetch, indices))
                in_flight += 1
                return True

            for _ in range(limit):
                if not submit_next():
                    break
            while in_flight:
                fut = pending.get()
                in_flight -= 1
                submit_next()
                # the prefetch span is the stall: time the consumer spent
                # blocked on a worker batch (0 when workers keep up)
                with _span("dataloader.prefetch"):
                    batch = fut.result()
                yield batch
