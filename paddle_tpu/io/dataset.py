"""Datasets + samplers (parity: python/paddle/fluid/dataloader/{dataset,
batch_sampler,sampler}.py)."""
from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self._cum[-1]

    def __getitem__(self, idx):
        di = bisect.bisect_right(self._cum, idx)
        prev = 0 if di == 0 else self._cum[di - 1]
        return self.datasets[di][idx - prev]


class ConcatDataset(ChainDataset):
    pass


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(len(p), self.num_samples, replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Parity: python/paddle/fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Parity: python/paddle/fluid/dataloader/batch_sampler.py
    DistributedBatchSampler — per-rank shard of the index space. On TPU this
    feeds per-host data for multi-host pjit (each host loads its slice of the
    global batch)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        if num_replicas is None or rank is None:
            from ..distributed.env import get_rank, get_world_size

            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        n = len(dataset)
        self.num_samples = (n + self.nranks - 1) // self.nranks
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make evenly divisible (paddle parity)
        indices += indices[: self.total_size - n]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
