"""Multiprocess DataLoader workers.

Parity: the reference's ``_DataLoaderIterMultiProcess``
(python/paddle/fluid/dataloader/dataloader_iter.py:342) + ``_worker_loop``
(dataloader/worker.py) + shared-memory LoDTensor transport
(fluid/memory/allocation/mmap_allocator). TPU-first shape of the same idea:

- worker processes pull ``(batch_idx, indices)`` off an index queue, run
  ``collate_fn([dataset[i] for i in indices])``, and ship each numpy array
  back through a POSIX shared-memory segment (``multiprocessing.
  shared_memory``) so large batches never pass through a pickle pipe;
- the parent restores order by batch index, detects dead workers instead of
  blocking forever, re-raises worker exceptions with their tracebacks, and
  supports persistent workers across epochs;
- workers never touch jax: fork inherits the parent's initialized backend,
  and the child exits with ``os._exit`` to skip jax/XLA atexit hooks.
"""
from __future__ import annotations

import os
import queue as pyqueue
import traceback

import numpy as np

_POLL_S = 2.0  # liveness-check cadence while waiting on the data queue


class WorkerInfo:
    """Worker-side view for IterableDataset sharding (reference
    dataloader/worker.py WorkerInfo / paddle.io.get_worker_info)."""

    def __init__(self, id, num_workers, dataset=None, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info: WorkerInfo | None = None


def get_worker_info():
    """Inside a process-mode worker: its WorkerInfo (with ``seed`` =
    pool base_seed + worker id, reference worker.py semantics); in the
    main process — and in thread-mode workers, which share the main
    process — None. Process mode is the only mode that runs worker code
    in a separate process, so it is the only mode with a worker-side
    view to report."""
    return _worker_info


class _ShmRef:
    """Descriptor for one ndarray parked in a shared-memory segment."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name, shape, dtype):
        self.name, self.shape, self.dtype = name, shape, str(dtype)


def _pack(obj, use_shm):
    """Replace ndarray leaves with _ShmRef descriptors (arrays copied into
    fresh shm segments); small/non-array leaves travel inline."""
    if use_shm and isinstance(obj, np.ndarray) and obj.nbytes > 0:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
        ref = _ShmRef(shm.name, obj.shape, obj.dtype)
        shm.close()  # segment lives until the parent unlinks it
        return ref
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v, use_shm) for v in obj)
    if isinstance(obj, dict):
        return {k: _pack(v, use_shm) for k, v in obj.items()}
    return obj


def _unpack(obj):
    if isinstance(obj, _ShmRef):
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=obj.name)
        try:
            arr = np.array(np.ndarray(obj.shape, obj.dtype, buffer=shm.buf))
        finally:
            shm.close()
            shm.unlink()
        return arr
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    if isinstance(obj, dict):
        return {k: _unpack(v) for k, v in obj.items()}
    return obj


def _discard(obj):
    """Unlink shm segments of a batch that will never be consumed."""
    if isinstance(obj, _ShmRef):
        from multiprocessing import shared_memory

        try:
            shm = shared_memory.SharedMemory(name=obj.name)
            shm.close()
            shm.unlink()
        except Exception:
            pass
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            _discard(v)
    elif isinstance(obj, dict):
        for v in obj.values():
            _discard(v)


def _worker_loop(dataset, collate_fn, index_q, data_q, worker_id, num_workers,
                 worker_init_fn, use_shm, base_seed=0):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset,
                              seed=base_seed + worker_id)
    try:
        try:
            if worker_init_fn is not None:
                worker_init_fn(worker_id)
        except Exception as exc:
            # key=None marks a fatal worker-level failure (not tied to a
            # batch) so the parent reports the real traceback, not a
            # misleading "killed (OOM/segfault)" hint
            data_q.put((None, None,
                        f"worker {worker_id} init failed — {type(exc).__name__}: "
                        f"{exc}\n{traceback.format_exc()}"))
            data_q.close()
            data_q.join_thread()  # flush before os._exit kills the feeder
            os._exit(1)
        while True:
            item = index_q.get()
            if item is None:
                break
            key, indices = item  # key = (epoch, batch_idx)
            try:
                batch = collate_fn([dataset[i] for i in indices])
                data_q.put((key, _pack(batch, use_shm), None))
            except Exception as exc:  # ship the traceback, keep serving
                data_q.put((key, None,
                            f"{type(exc).__name__}: {exc}\n{traceback.format_exc()}"))
    except (KeyboardInterrupt, SystemExit):
        # interrupted: drop whatever the feeder still buffers — flushing
        # could block forever on a parent that is itself dying
        data_q.cancel_join_thread()
    else:
        # graceful (sentinel) exit: flush buffered shm batches so the
        # parent can drain and unlink them instead of leaking segments
        data_q.close()
        data_q.join_thread()
    finally:
        os._exit(0)  # skip jax/XLA atexit hooks inherited through fork


class WorkerPool:
    """Persistent fork-based worker pool + ordered batch iteration."""

    def __init__(self, dataset, collate_fn, num_workers, worker_init_fn=None,
                 use_shm=True, timeout=0, prefetch_factor=2, base_seed=None):
        import multiprocessing as mp

        if base_seed is None:
            # one base per pool; worker i sees base_seed + i (reference
            # worker.py derives per-worker seeds the same way)
            base_seed = int.from_bytes(os.urandom(4), "little")
        self.base_seed = base_seed

        ctx = mp.get_context("fork" if "fork" in mp.get_all_start_methods() else "spawn")
        self._ctx = ctx
        try:
            # start the resource tracker BEFORE forking so workers inherit it:
            # shm segments registered by workers then unregister cleanly when
            # the parent unlinks them (otherwise each worker spawns its own
            # tracker, which warns about already-unlinked segments at exit)
            from multiprocessing import resource_tracker

            resource_tracker.ensure_running()
        except Exception:
            pass
        self._timeout = timeout
        self._prefetch = max(1, prefetch_factor)
        self._index_q = ctx.Queue()
        self._data_q = ctx.Queue()
        self._closed = False
        self._active = False  # one in-flight epoch per pool
        self._epoch = 0  # tags queue traffic so a half-consumed epoch's
        self._workers = []  # leftovers can't leak into the next one
        for wid in range(num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(dataset, collate_fn, self._index_q, self._data_q,
                      wid, num_workers, worker_init_fn, use_shm,
                      self.base_seed),
                daemon=True)
            p.start()
            self._workers.append(p)

    def run_epoch(self, index_batches):
        """Generator: feed the index queue, yield collated batches in order.

        Backpressure: at most ``num_workers * prefetch_factor`` index batches
        are outstanding (reference keeps the same bound), so workers can't
        park an unbounded epoch's worth of batches in /dev/shm ahead of a
        slow consumer. Results tagged with an older epoch (a previous
        iterator abandoned mid-epoch) are discarded, shm segments included.
        """
        if self._active:
            raise RuntimeError(
                "this DataLoader's process-worker pool is already serving an "
                "iterator; with persistent_workers only one epoch can be "
                "in flight (exhaust or close the first iterator, or use "
                "thread mode for concurrent iteration)")
        self._active = True
        self._epoch += 1
        epoch = self._epoch
        batches = [list(ix) for ix in index_batches]
        total = len(batches)
        window = min(len(self._workers) * self._prefetch, total)
        sent = 0
        for sent in range(window):
            self._index_q.put(((epoch, sent), batches[sent]))
        sent = window
        reorder = {}

        def _fail(msg):
            self.shutdown()
            raise RuntimeError(msg)

        try:
            yield from self._epoch_loop(epoch, batches, total, sent, reorder, _fail)
        finally:
            # early close / error: unlink shm parked in the reorder buffer,
            # it is unreachable from both the queue and the next epoch
            for payload, _ in reorder.values():
                _discard(payload)
            reorder.clear()
            self._active = False

    def _epoch_loop(self, epoch, batches, total, sent, reorder, _fail):
        received = 0
        next_idx = 0
        waited = 0.0
        while next_idx < total:
            while next_idx in reorder:
                payload, err = reorder.pop(next_idx)
                next_idx += 1
                if err is not None:
                    _fail(f"DataLoader worker raised:\n{err}")
                yield _unpack(payload)
            if next_idx >= total:
                break
            try:
                key, payload, err = self._data_q.get(timeout=_POLL_S)
                if key is None:  # fatal worker-level failure (e.g. init)
                    _discard(payload)
                    _fail(f"DataLoader worker failed:\n{err}")
                ep, bidx = key
                if ep != epoch:
                    _discard(payload)
                    continue
                waited = 0.0
                received += 1
                reorder[bidx] = (payload, err)
                if sent < total:  # top up the window per receive
                    self._index_q.put(((epoch, sent), batches[sent]))
                    sent += 1
            except pyqueue.Empty:
                waited += _POLL_S
                dead = [p for p in self._workers if not p.is_alive()]
                if dead:
                    # a worker may have flushed its own fatal report just
                    # before dying — prefer that over the generic hint
                    try:
                        while True:
                            key, payload, err = self._data_q.get_nowait()
                            if key is None:
                                _discard(payload)
                                _fail(f"DataLoader worker failed:\n{err}")
                            ep, bidx = key
                            if ep != epoch:
                                _discard(payload)
                            else:
                                reorder[bidx] = (payload, err)
                    except pyqueue.Empty:
                        pass
                    p = dead[0]
                    _fail(f"DataLoader worker (pid {p.pid}) exited unexpectedly "
                          f"with code {p.exitcode}. This usually means the "
                          "worker was killed (OOM/segfault) — rerun with "
                          "num_workers=0 to debug in the main process.")
                if self._timeout and waited >= self._timeout:
                    _fail(f"DataLoader timed out after {self._timeout}s waiting "
                          f"for batch {next_idx} ({received}/{total} received)")

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        # stop workers FIRST (data_q is unbounded so their in-flight puts
        # can't block), then drain every unconsumed shm batch — draining
        # before the join would miss batches workers finish during it
        for _ in self._workers:
            try:
                self._index_q.put(None)
            except Exception:
                pass
        for p in self._workers:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        try:
            while True:
                _, payload, _ = self._data_q.get_nowait()
                _discard(payload)
        except Exception:
            pass
        self._index_q.close()
        self._data_q.close()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
