"""Native record-file data feed: C++ reader threads → numpy batches.

Parity: the reference's C++ dataset pipeline (``paddle/fluid/framework/
data_feed.cc`` readers + ``data_set.cc`` file sharding + channels, surfaced in
Python as ``paddle.distributed.QueueDataset``/``InMemoryDataset``). TPU-first
shape: fixed-size binary records (one sample = one struct of fixed-shape
fields) read, block-shuffled and batched entirely in native threads
(csrc/data_feed.cc) with no GIL on the hot path; Python receives ready
batch buffers and views them as numpy arrays for jax.device_put.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..framework import native


class RecordSchema:
    """Describes one fixed-size record: ordered (name, dtype, shape) fields."""

    def __init__(self, fields: Sequence[Tuple[str, str, Sequence[int]]]):
        self.fields = [(n, np.dtype(d), tuple(int(s) for s in shape)) for n, d, shape in fields]
        self.record_bytes = sum(dt.itemsize * int(np.prod(shape, dtype=np.int64).item() or 1)
                                for _, dt, shape in self.fields)

    def write_records(self, path: str, columns: Dict[str, np.ndarray]) -> int:
        """Serialize sample-major columns into a record file; returns count."""
        converted = []
        n = None
        for name, dt, shape in self.fields:
            arr = np.ascontiguousarray(columns[name], dtype=dt)
            if arr.shape[1:] != shape:
                raise ValueError(f"field {name}: expected trailing shape {shape}, got {arr.shape[1:]}")
            n = arr.shape[0] if n is None else n
            if arr.shape[0] != n:
                raise ValueError("all columns must share the leading (sample) dim")
            converted.append(arr.reshape(n, -1).view(np.uint8).reshape(n, -1))
        # interleave fields sample-major in one shot: (n, record_bytes) matrix
        packed = np.concatenate(converted, axis=1) if len(converted) > 1 else converted[0]
        with open(path, "wb") as f:
            f.write(np.ascontiguousarray(packed).tobytes())
        return n

    def decode_batch(self, buf: bytes) -> Dict[str, np.ndarray]:
        """Split a batch of packed records back into per-field arrays."""
        nrec, rem = divmod(len(buf), self.record_bytes)
        if rem:
            raise ValueError(f"batch of {len(buf)} bytes is not a multiple of record size {self.record_bytes}")
        raw = np.frombuffer(buf, dtype=np.uint8).reshape(nrec, self.record_bytes)
        out = {}
        off = 0
        for name, dt, shape in self.fields:
            nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64).item() or 1)
            field = raw[:, off:off + nbytes].reshape(-1).view(dt).reshape((nrec,) + shape)
            out[name] = field
            off += nbytes
        return out


class RecordFileLoader:
    """Iterable over native-read batches of records from sharded files.

    One epoch per iteration; ``shuffle`` is a bounded-memory block shuffle in
    the native readers (reference data_feed shuffling semantics).
    """

    def __init__(self, files: List[str], schema: RecordSchema, batch_size: int,
                 num_workers: int = 2, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = False, queue_capacity: int = 8):
        if not files:
            raise ValueError("RecordFileLoader needs at least one file")
        self.schema = schema
        self.batch_size = int(batch_size)
        self._lib = native.load_native()
        self._h = self._lib.pt_feed_create(
            "\n".join(files).encode(), schema.record_bytes, self.batch_size,
            int(num_workers), int(queue_capacity), 1 if shuffle else 0,
            int(seed), 1 if drop_last else 0)
        if not self._h:
            raise ValueError("invalid feed configuration")

    def __iter__(self):
        if getattr(self, "_iterating", False):
            raise RuntimeError(
                "RecordFileLoader supports one active iterator: the native feed "
                "is a single stream; restarting it would corrupt the in-flight epoch")
        self._iterating = True
        try:
            self._lib.pt_feed_start_epoch(self._h)
            while True:
                out = ctypes.c_void_p()
                n = self._lib.pt_feed_next(self._h, ctypes.byref(out))
                if n == 0:
                    return
                buf = ctypes.string_at(out, n)
                self._lib.pt_buffer_free(out)
                yield self.schema.decode_batch(buf)
        finally:
            self._iterating = False

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_feed_destroy(self._h)
            self._h = None
