"""paddle_tpu.io (parity: python/paddle/io)."""
from .dataloader import DataLoader, default_collate_fn, stack_batches  # noqa: F401
from .mp_worker import WorkerInfo, get_worker_info  # noqa: F401
from .dataset import (  # noqa: F401
    BatchSampler,
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    DistributedBatchSampler,
    IterableDataset,
    RandomSampler,
    Sampler,
    SequenceSampler,
    Subset,
    TensorDataset,
    WeightedRandomSampler,
    random_split,
)
from .record_feed import RecordFileLoader, RecordSchema  # noqa: F401
