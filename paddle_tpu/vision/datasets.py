"""Vision datasets (parity: python/paddle/vision/datasets + paddle/dataset).

The build env has no network egress, so MNIST/CIFAR load from local files
when present and otherwise fall back to deterministic synthetic data of the
right shape — keeping example/bench code runnable anywhere.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=False, backend=None, synthetic_size=60000):
        self.transform = transform
        self.mode = mode
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(n, 1, rows, cols).astype(np.float32) / 255.0
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = synthetic_size if mode == "train" else synthetic_size // 6
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so a model can actually learn
            base = rng.randn(10, 1, 28, 28).astype(np.float32)
            noise = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.3
            self.images = base[self.labels] + noise

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=False, backend=None, synthetic_size=50000):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = synthetic_size if mode == "train" else synthetic_size // 5
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        base = rng.randn(10, 3, 32, 32).astype(np.float32)
        self.images = base[self.labels] + rng.randn(n, 3, 32, 32).astype(np.float32) * 0.3
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)
