"""paddle_tpu.vision (parity: python/paddle/vision) — models live in
paddle_tpu.models; datasets here are synthetic/local-file based (no network
in the build environment)."""
from . import datasets  # noqa: F401
from . import transforms  # noqa: F401


def models():
    from .. import models as m

    return m
