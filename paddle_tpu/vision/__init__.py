"""paddle_tpu.vision (parity: python/paddle/vision) — datasets are
synthetic/local-file based (no network in the build environment)."""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401
