"""Image transform functionals (parity: python/paddle/vision/transforms/
functional.py + functional_tensor.py).

Host-side numpy image ops — transforms run in the input pipeline (DataLoader
workers), never on the accelerator, matching the reference's cv2/PIL
backends. Images are HWC numpy arrays (uint8 or float) or CHW Tensors;
every op keeps the input container type.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor, _wrap_value, unwrap


def _as_np(img):
    if isinstance(img, Tensor):
        return np.asarray(unwrap(img)), True
    return np.asarray(img), False


def _back(arr, was_tensor):
    if was_tensor:
        import jax.numpy as jnp

        return _wrap_value(jnp.asarray(arr))
    return arr


def to_tensor(pic, data_format="CHW"):
    """HWC uint8/float image -> float32 Tensor scaled to [0, 1]
    (reference functional.to_tensor)."""
    import jax.numpy as jnp

    arr = np.asarray(pic)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return _wrap_value(jnp.asarray(arr.astype(np.float32)))


def hflip(img):
    arr, t = _as_np(img)
    return _back(arr[..., ::-1] if t else arr[:, ::-1], t)


def vflip(img):
    arr, t = _as_np(img)
    return _back(arr[..., ::-1, :] if t else arr[::-1], t)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr, t = _as_np(img)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    shape = (-1, 1, 1) if data_format == "CHW" else (1, 1, -1)
    out = (arr.astype(np.float32) - mean.reshape(shape)) / std.reshape(shape)
    return _back(out, t)


def crop(img, top, left, height, width):
    arr, t = _as_np(img)
    if t:  # CHW
        return _back(arr[..., top:top + height, left:left + width], t)
    return _back(arr[top:top + height, left:left + width], t)


def center_crop(img, output_size):
    arr, t = _as_np(img)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else output_size
    h, w = (arr.shape[-2], arr.shape[-1]) if t else (arr.shape[0], arr.shape[1])
    top = max((h - oh) // 2, 0)
    left = max((w - ow) // 2, 0)
    return crop(img, top, left, oh, ow)


def resize(img, size, interpolation="bilinear"):
    """Nearest/bilinear resize on the host (reference functional.resize).
    ``size``: int (short side) or (h, w)."""
    arr, t = _as_np(img)
    chw = t
    a = np.transpose(arr, (1, 2, 0)) if chw else arr
    if a.ndim == 2:
        a = a[:, :, None]
    h, w = a.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    if interpolation == "nearest":
        yi = (np.arange(oh) * h / oh).astype(int).clip(0, h - 1)
        xi = (np.arange(ow) * w / ow).astype(int).clip(0, w - 1)
        out = a[yi][:, xi]
    else:  # bilinear
        fy = (np.arange(oh) + 0.5) * h / oh - 0.5
        fx = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.floor(fy).astype(int).clip(0, h - 1)
        x0 = np.floor(fx).astype(int).clip(0, w - 1)
        y1 = (y0 + 1).clip(0, h - 1)
        x1 = (x0 + 1).clip(0, w - 1)
        wy = (fy - y0).clip(0, 1)[:, None, None]
        wx = (fx - x0).clip(0, 1)[None, :, None]
        af = a.astype(np.float32)
        out = (af[y0][:, x0] * (1 - wy) * (1 - wx) + af[y0][:, x1] * (1 - wy) * wx
               + af[y1][:, x0] * wy * (1 - wx) + af[y1][:, x1] * wy * wx)
        if arr.dtype == np.uint8:
            out = np.round(out).clip(0, 255).astype(np.uint8)
        else:
            out = out.astype(arr.dtype)
    out = np.squeeze(out, -1) if (not chw and arr.ndim == 2) else out
    return _back(np.transpose(out, (2, 0, 1)) if chw else out, t)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr, t = _as_np(img)
    if isinstance(padding, int):
        l = r = tp = b = padding
    elif len(padding) == 2:
        (l, tp), (r, b) = (padding[0], padding[1]), (padding[0], padding[1])
    else:
        l, tp, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    if t:  # CHW
        pads = [(0, 0)] * (arr.ndim - 2) + [(tp, b), (l, r)]
    else:
        pads = [(tp, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    return _back(np.pad(arr, pads, mode=mode, **kw), t)


def adjust_brightness(img, brightness_factor):
    arr, t = _as_np(img)
    out = arr.astype(np.float32) * brightness_factor
    out = out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out.astype(arr.dtype)
    return _back(out, t)


def adjust_contrast(img, contrast_factor):
    arr, t = _as_np(img)
    gray_mean = _gray(arr, t).mean()
    out = arr.astype(np.float32) * contrast_factor + gray_mean * (1 - contrast_factor)
    out = out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out.astype(arr.dtype)
    return _back(out, t)


def _gray(arr, chw):
    w = np.asarray([0.299, 0.587, 0.114], np.float32)
    a = arr.astype(np.float32)
    if chw:
        return np.tensordot(w, a, axes=([0], [0]))
    return a @ w


def to_grayscale(img, num_output_channels=1):
    arr, t = _as_np(img)
    g = _gray(arr, t)
    if t:
        out = np.repeat(g[None], num_output_channels, 0)
    else:
        out = np.repeat(g[..., None], num_output_channels, -1)
    out = out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out.astype(arr.dtype)
    return _back(out, t)


def adjust_saturation(img, saturation_factor):
    arr, t = _as_np(img)
    g = _gray(arr, t)
    g = g[None] if t else g[..., None]
    out = arr.astype(np.float32) * saturation_factor + g * (1 - saturation_factor)
    out = out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out.astype(arr.dtype)
    return _back(out, t)


def adjust_hue(img, hue_factor):
    """Rotate hue by hue_factor (in [-0.5, 0.5]) via RGB<->HSV
    (reference functional.adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr, t = _as_np(img)
    a = (np.moveaxis(arr, 0, -1) if t else arr).astype(np.float32)
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    a = a / scale
    mx, mn = a.max(-1), a.min(-1)
    d = mx - mn + 1e-8
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    h = np.where(mx == r, ((g - b) / d) % 6, np.where(mx == g, (b - r) / d + 2, (r - g) / d + 4)) / 6
    h = (h + hue_factor) % 1.0
    s = np.where(mx > 0, d / (mx + 1e-8), 0)
    v = mx
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, tt = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i.astype(int) % 6
    conds = [(i == k)[..., None] for k in range(6)]
    out = np.select(conds,
                    [np.stack([v, tt, p], -1), np.stack([q, v, p], -1), np.stack([p, v, tt], -1),
                     np.stack([p, q, v], -1), np.stack([tt, p, v], -1), np.stack([v, p, q], -1)])
    out = out * scale
    out = out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out.astype(arr.dtype)
    return _back(np.moveaxis(out, -1, 0) if t else out, t)


def erase(img, i, j, h, w, v, inplace=False):
    arr, t = _as_np(img)
    # jax-backed arrays are read-only views; true in-place only works for
    # writable ndarrays
    out = arr if (inplace and not t and arr.flags.writeable) else arr.copy()
    if t:
        out[..., i:i + h, j:j + w] = v
    else:
        out[i:i + h, j:j + w] = v
    return _back(out, t)


def _affine_sample(arr, chw, mat, out_hw, interpolation="nearest", fill=0):
    """Inverse-map sampling with a 2x3 matrix in pixel coords; nearest or
    bilinear interpolation."""
    a = np.moveaxis(arr, 0, -1) if chw else arr
    squeeze = a.ndim == 2
    if squeeze:
        a = a[:, :, None]
    H, W = out_hw
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    sx = mat[0, 0] * xs + mat[0, 1] * ys + mat[0, 2]
    sy = mat[1, 0] * xs + mat[1, 1] * ys + mat[1, 2]

    def gather(yi, xi):
        inb = (xi >= 0) & (xi < a.shape[1]) & (yi >= 0) & (yi < a.shape[0])
        vals = a[yi.clip(0, a.shape[0] - 1), xi.clip(0, a.shape[1] - 1)].astype(np.float32)
        return np.where(inb[..., None], vals, np.float32(fill))

    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(int)
        y0 = np.floor(sy).astype(int)
        wx = (sx - x0)[..., None]
        wy = (sy - y0)[..., None]
        out = (gather(y0, x0) * (1 - wy) * (1 - wx) + gather(y0, x0 + 1) * (1 - wy) * wx
               + gather(y0 + 1, x0) * wy * (1 - wx) + gather(y0 + 1, x0 + 1) * wy * wx)
    else:
        out = gather(np.round(sy).astype(int), np.round(sx).astype(int))
    if arr.dtype == np.uint8:
        out = np.round(out).clip(0, 255).astype(np.uint8)
    else:
        out = out.astype(arr.dtype)
    if squeeze:
        out = out[:, :, 0]
    return np.moveaxis(out, -1, 0) if chw else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    arr, t = _as_np(img)
    h, w = (arr.shape[-2:] if t else arr.shape[:2])
    rad = np.deg2rad(angle)
    c, s = np.cos(rad), np.sin(rad)
    oh, ow = h, w
    if expand:
        # canvas grows to hold the rotated extent; rotation recentered
        ow = int(np.ceil(round(abs(w * c) + abs(h * s), 10)))
        oh = int(np.ceil(round(abs(w * s) + abs(h * c), 10)))
        center = None  # expand always rotates about the image center
    cx, cy = center if center is not None else (w / 2, h / 2)
    ocx, ocy = (ow / 2, oh / 2) if expand else (cx, cy)
    # inverse rotation: output pixel -> source pixel about the centers
    mat = np.array([[c, s, cx - c * ocx - s * ocy],
                    [-s, c, cy + s * ocx - c * ocy]], np.float32)
    return _back(_affine_sample(arr, t, mat, (oh, ow), interpolation, fill), t)


def affine(img, angle=0, translate=(0, 0), scale=1.0, shear=(0, 0), interpolation="nearest", center=None, fill=0):
    arr, t = _as_np(img)
    h, w = (arr.shape[-2:] if t else arr.shape[:2])
    cx, cy = center if center is not None else (w / 2, h / 2)
    rad = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward matrix R(angle) @ Shear @ scale, then invert for sampling
    a = scale * np.cos(rad + sy) / max(np.cos(sy), 1e-8)
    b = scale * (np.cos(rad + sy) * np.tan(sx) / max(np.cos(sy), 1e-8) - np.sin(rad))
    c = scale * np.sin(rad + sy) / max(np.cos(sy), 1e-8)
    d = scale * (np.sin(rad + sy) * np.tan(sx) / max(np.cos(sy), 1e-8) + np.cos(rad))
    fwd = np.array([[a, b, 0.0], [c, d, 0.0], [0, 0, 1.0]], np.float32)
    inv = np.linalg.inv(fwd)
    tx, ty = translate
    mat = np.array([[inv[0, 0], inv[0, 1], 0], [inv[1, 0], inv[1, 1], 0]], np.float32)
    mat[:, 2] = [cx - mat[0, 0] * (cx + tx) - mat[0, 1] * (cy + ty),
                 cy - mat[1, 0] * (cx + tx) - mat[1, 1] * (cy + ty)]
    return _back(_affine_sample(arr, t, mat, (h, w), interpolation, fill), t)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Four-point perspective warp (reference functional.perspective)."""
    arr, t = _as_np(img)
    h, w = (arr.shape[-2:] if t else arr.shape[:2])
    # solve homography mapping endpoints -> startpoints (inverse sampling)
    A, bvec = [], []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        bvec.append(sx)
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec.append(sy)
    hvec = np.linalg.solve(np.asarray(A, np.float64), np.asarray(bvec, np.float64))
    Hm = np.append(hvec, 1.0).reshape(3, 3)
    a = np.moveaxis(arr, 0, -1) if t else arr
    squeeze = a.ndim == 2
    if squeeze:
        a = a[:, :, None]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = Hm[2, 0] * xs + Hm[2, 1] * ys + Hm[2, 2]
    sxs = (Hm[0, 0] * xs + Hm[0, 1] * ys + Hm[0, 2]) / den
    sys_ = (Hm[1, 0] * xs + Hm[1, 1] * ys + Hm[1, 2]) / den
    xi, yi = np.round(sxs).astype(int), np.round(sys_).astype(int)
    inb = (xi >= 0) & (xi < a.shape[1]) & (yi >= 0) & (yi < a.shape[0])
    out = np.full((h, w, a.shape[2]), fill, a.dtype)
    out[inb] = a[yi.clip(0, a.shape[0] - 1), xi.clip(0, a.shape[1] - 1)][inb]
    if squeeze:
        out = out[:, :, 0]
    return _back(np.moveaxis(out, -1, 0) if t else out, t)
