"""Detection operators (parity: python/paddle/vision/ops.py — yolo_box,
roi_align, roi_pool, nms, deform_conv2d, ...).

TPU-first notes:
- roi_align / roi_pool: bilinear-gather formulations — static shapes, all
  gathers, XLA-fusable (no dynamic loops, unlike the CUDA kernels'
  per-box threads).
- nms: the sequential greedy suppression runs as a lax.fori_loop over a
  fixed box count — O(n²) IoU matrix + mask accumulation, compiled once;
  data-dependent survivor COUNT is resolved on the host at the end (the
  only inherently dynamic part).
- yolo_box: pure elementwise decode of the grid predictions.
"""
from __future__ import annotations

import numpy as np

from ..tensor._helpers import ensure_tensor, op

__all__ = ["nms", "roi_align", "roi_pool", "yolo_box"]


def _iou_matrix(boxes):
    import jax.numpy as jnp

    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None])
    iy1 = jnp.maximum(y1[:, None], y1[None])
    ix2 = jnp.minimum(x2[:, None], x2[None])
    iy2 = jnp.minimum(y2[:, None], y2[None])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Greedy NMS (reference vision/ops.py:1395). Returns kept indices,
    sorted by descending score. With ``category_idxs``, suppression is
    per-category (multiclass NMS)."""
    import jax
    import jax.numpy as jnp

    boxes = ensure_tensor(boxes)
    n = int(boxes._value.shape[0])

    def kern(bv, sv, cv):
        order = jnp.argsort(-sv)
        bo = jnp.take(bv, order, axis=0)
        iou = _iou_matrix(bo)
        if cv is not None:
            co = jnp.take(cv, order)
            iou = jnp.where(co[:, None] == co[None], iou, 0.0)

        def body(i, keep):
            # keep box i iff no higher-scored KEPT box overlaps it
            sup = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
            return keep.at[i].set(~jnp.any(sup))

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        return keep, order

    sv = ensure_tensor(scores) if scores is not None else None
    cv = ensure_tensor(category_idxs) if category_idxs is not None else None

    def fn(bv, *rest):
        it = iter(rest)
        s = next(it) if sv is not None else jnp.zeros((n,), bv.dtype)
        c = next(it) if cv is not None else None
        return kern(bv, s, c)

    args = [boxes] + ([sv] if sv is not None else []) + ([cv] if cv is not None else [])
    keep_t, order_t = op(fn, *args, _name="nms")
    keep = np.asarray(keep_t.numpy())
    order = np.asarray(order_t.numpy())
    kept = order[keep]  # survivors in score order (host-side dynamic shape)
    if top_k is not None:
        kept = kept[: int(top_k)]
    from ..framework.core import _wrap_value
    import jax.numpy as jnp2

    return _wrap_value(jnp2.asarray(kept.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference vision/ops.py:1181): bilinear sampling of each
    box on an output_size grid, averaged over sampling points."""
    import jax.numpy as jnp

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)

    def fn(feat, bxs, bnum):
        N, C, H, W = feat.shape
        n_boxes = bxs.shape[0]
        # map each box to its batch image from boxes_num (cumulative)
        bounds = jnp.cumsum(bnum)
        batch_idx = jnp.sum(jnp.arange(n_boxes)[:, None] >= bounds[None, :], axis=1)

        offset = 0.5 if aligned else 0.0
        xs1 = bxs[:, 0] * spatial_scale - offset
        ys1 = bxs[:, 1] * spatial_scale - offset
        xs2 = bxs[:, 2] * spatial_scale - offset
        ys2 = bxs[:, 3] * spatial_scale - offset
        bw = xs2 - xs1
        bh = ys2 - ys1
        if not aligned:
            bw = jnp.maximum(bw, 1.0)
            bh = jnp.maximum(bh, 1.0)

        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [n_boxes, ph*sr] y coords, [n_boxes, pw*sr] x coords
        gy = (jnp.arange(ph * sr) + 0.5) / sr  # in bin units
        gx = (jnp.arange(pw * sr) + 0.5) / sr
        ys = ys1[:, None] + bh[:, None] * gy[None] / ph
        xs = xs1[:, None] + bw[:, None] * gx[None] / pw

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [Py], xx [Px] -> [C, Py, Px]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1 = jnp.clip(y0 + 1, 0, H - 1)
            x1 = jnp.clip(x0 + 1, 0, W - 1)
            wy1 = jnp.clip(yy, 0, H - 1) - y0
            wx1 = jnp.clip(xx, 0, W - 1) - x0
            y0i, y1i, x0i, x1i = y0.astype(int), y1.astype(int), x0.astype(int), x1.astype(int)
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy1)[None, :, None] * (1 - wx1)[None, None, :]
                    + v01 * (1 - wy1)[None, :, None] * wx1[None, None, :]
                    + v10 * wy1[None, :, None] * (1 - wx1)[None, None, :]
                    + v11 * wy1[None, :, None] * wx1[None, None, :])

        import jax

        def per_box(b):
            img = feat[batch_idx[b]]
            samp = bilinear(img, ys[b], xs[b])  # [C, ph*sr, pw*sr]
            return samp.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))

        return jax.vmap(per_box)(jnp.arange(n_boxes))

    return op(fn, x, boxes, boxes_num, _name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (max pooling per bin; reference vision/ops.py:1053) via a
    dense-sampled max (8 samples per bin edge approximates the exact
    integer-bin max with static shapes)."""
    import jax
    import jax.numpy as jnp

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)

    def fn(feat, bxs, bnum):
        N, C, H, W = feat.shape
        n_boxes = bxs.shape[0]
        bounds = jnp.cumsum(bnum)
        batch_idx = jnp.sum(jnp.arange(n_boxes)[:, None] >= bounds[None, :], axis=1)
        sr = 8
        gy = jnp.arange(ph * sr) / sr
        gx = jnp.arange(pw * sr) / sr

        def per_box(b):
            img = feat[batch_idx[b]]
            x1 = bxs[b, 0] * spatial_scale
            y1 = bxs[b, 1] * spatial_scale
            x2 = jnp.maximum(bxs[b, 2] * spatial_scale, x1 + 1)
            y2 = jnp.maximum(bxs[b, 3] * spatial_scale, y1 + 1)
            ys = jnp.clip(jnp.round(y1 + (y2 - y1) * gy / ph), 0, H - 1).astype(int)
            xs = jnp.clip(jnp.round(x1 + (x2 - x1) * gx / pw), 0, W - 1).astype(int)
            samp = img[:, ys][:, :, xs]
            return samp.reshape(C, ph, sr, pw, sr).max(axis=(2, 4))

        return jax.vmap(per_box)(jnp.arange(n_boxes))

    return op(fn, x, boxes, boxes_num, _name="roi_pool")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio=32,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head predictions to boxes+scores (reference
    vision/ops.py:252). x: [N, A*(5+class_num), H, W]; returns
    (boxes [N, A*H*W, 4] in xyxy, scores [N, A*H*W, class_num])."""
    import jax.numpy as jnp

    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    x = ensure_tensor(x)
    img_size = ensure_tensor(img_size)

    def fn(xv, isz):
        N, _, H, W = xv.shape
        p = xv.reshape(N, A, 5 + class_num, H, W)
        cx = (jnp.arange(W))[None, None, None, :]
        cy = (jnp.arange(H))[None, None, :, None]
        sig = lambda v: 1 / (1 + jnp.exp(-v))
        bx = (sig(p[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1.0) + cx) / W
        by = (sig(p[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1.0) + cy) / H
        bw = jnp.exp(p[:, :, 2]) * anchors[None, :, 0, None, None] / (downsample_ratio * W)
        bh = jnp.exp(p[:, :, 3]) * anchors[None, :, 1, None, None] / (downsample_ratio * H)
        obj = sig(p[:, :, 4])
        cls = sig(p[:, :, 5:])
        score = obj[:, :, None] * cls  # [N, A, class, H, W]
        imgh = isz[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = isz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, A * H * W, 4)
        score = jnp.moveaxis(score, 2, -1).reshape(N, A * H * W, class_num)
        keep = (obj.reshape(N, A * H * W) > conf_thresh)[..., None]
        return boxes * keep, score * keep

    import jax

    return op(fn, x, img_size, _name="yolo_box")


# -- round-4 ops tail --------------------------------------------------------


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference psroi_pool_op, R-FCN):
    input channels are grouped as [out_c, ph, pw]; bin (i, j) of the output
    average-pools its own channel group over that spatial bin."""
    import jax.numpy as jnp

    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size

    def fn(v, bx, bn):
        N, C, H, W = v.shape
        out_c = C // (ph * pw)
        R = bx.shape[0]
        # map each roi to its source image via boxes_num prefix sums (same
        # contract as roi_align)
        img_of = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(R), side="right")
        x1 = bx[:, 0] * spatial_scale
        y1 = bx[:, 1] * spatial_scale
        x2 = bx[:, 2] * spatial_scale
        y2 = bx[:, 3] * spatial_scale
        bh = jnp.maximum(y2 - y1, 0.1) / ph
        bw = jnp.maximum(x2 - x1, 0.1) / pw
        vg = v.reshape(N, out_c, ph, pw, H, W)
        ys = jnp.arange(H)[None, None, :]  # [1,1,H]
        xs = jnp.arange(W)[None, None, :]
        outs = []
        for i in range(ph):
            for j in range(pw):
                ys0 = (y1 + i * bh)[:, None]
                ys1 = (y1 + (i + 1) * bh)[:, None]
                xs0 = (x1 + j * bw)[:, None]
                xs1 = (x1 + (j + 1) * bw)[:, None]
                my = ((ys[0] >= jnp.floor(ys0)) & (ys[0] < jnp.ceil(ys1))).astype(v.dtype)  # [R,H]
                mx = ((xs[0] >= jnp.floor(xs0)) & (xs[0] < jnp.ceil(xs1))).astype(v.dtype)  # [R,W]
                m2 = my[:, :, None] * mx[:, None, :]  # [R,H,W]
                cnt = jnp.maximum(m2.sum((1, 2)), 1.0)  # [R]
                grp = vg[img_of, :, i, j]  # [R, out_c, H, W]
                pooled = jnp.einsum("rchw,rhw->rc", grp, m2) / cnt[:, None]
                outs.append(pooled)
        out = jnp.stack(outs, -1).reshape(R, out_c, ph, pw)
        return out

    return op(fn, ensure_tensor(x), ensure_tensor(boxes), ensure_tensor(boxes_num),
              _name="psroi_pool")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (reference deform_conv2d / deform_conv2d_op):
    bilinear-sample the input at offset-shifted tap locations, then a dense
    matmul per output position — gathers + one MXU contraction, no custom
    kernel."""
    import jax.numpy as jnp

    if groups != 1 or deformable_groups != 1:
        raise NotImplementedError("groups/deformable_groups > 1")
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    args = [ensure_tensor(x), ensure_tensor(offset), ensure_tensor(weight)]
    if mask is not None:
        args.append(ensure_tensor(mask))
    if bias is not None:
        args.append(ensure_tensor(bias))
    has_mask, has_bias = mask is not None, bias is not None

    def fn(v, off, w, *rest):
        mk = rest[0] if has_mask else None
        bs = rest[-1] if has_bias else None
        N, C, H, W = v.shape
        OC, IC, KH, KW = w.shape
        HO = (H + 2 * pd[0] - dl[0] * (KH - 1) - 1) // st[0] + 1
        WO = (W + 2 * pd[1] - dl[1] * (KW - 1) - 1) // st[1] + 1
        base_y = jnp.arange(HO)[:, None] * st[0] - pd[0]
        base_x = jnp.arange(WO)[None, :] * st[1] - pd[1]
        cols = []
        off = off.reshape(N, KH, KW, 2, HO, WO)
        for ki in range(KH):
            for kj in range(KW):
                dy = off[:, ki, kj, 0]
                dx = off[:, ki, kj, 1]
                sy = base_y[None] + ki * dl[0] + dy  # [N, HO, WO]
                sx = base_x[None] + kj * dl[1] + dx
                y0 = jnp.floor(sy)
                x0 = jnp.floor(sx)
                wy = sy - y0
                wx = sx - x0

                def g(yy, xx):
                    inb = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
                    yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                    xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                    vals = v[jnp.arange(N)[:, None, None], :, yc, xc]  # [N,HO,WO,C]
                    return jnp.where(inb[..., None], vals, 0.0)

                s = (g(y0, x0) * ((1 - wy) * (1 - wx))[..., None]
                     + g(y0, x0 + 1) * ((1 - wy) * wx)[..., None]
                     + g(y0 + 1, x0) * (wy * (1 - wx))[..., None]
                     + g(y0 + 1, x0 + 1) * (wy * wx)[..., None])
                if mk is not None:
                    mkk = mk.reshape(N, KH, KW, HO, WO)[:, ki, kj]
                    s = s * mkk[..., None]
                cols.append(s)  # [N,HO,WO,C]
        col = jnp.stack(cols, axis=3)  # [N,HO,WO,KH*KW,C]
        wflat = w.reshape(OC, IC, KH * KW).transpose(2, 1, 0)  # [KK, IC, OC]
        out = jnp.einsum("nhwkc,kco->nohw", col, wflat,
                         preferred_element_type=jnp.float32).astype(v.dtype)
        if bs is not None:
            out = out + bs.reshape(1, -1, 1, 1)
        return out

    return op(fn, *args, _name="deform_conv2d")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num, ignore_thresh,
              downsample_ratio, gt_score=None, use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference yolov3_loss_op): per-cell box regression
    (xy: bce, wh: l1), objectness with ignore threshold, class bce.
    Single-scale form over the masked anchors."""
    import jax
    import jax.numpy as jnp

    def fn(xv, gb, gl, *rest):
        gs = rest[0] if gt_score is not None else None
        N, _, Hc, Wc = xv.shape
        A = len(anchor_mask)
        pred = xv.reshape(N, A, 5 + class_num, Hc, Wc)
        px = jax.nn.sigmoid(pred[:, :, 0])
        py = jax.nn.sigmoid(pred[:, :, 1])
        pw = pred[:, :, 2]
        phh = pred[:, :, 3]
        pobj = pred[:, :, 4]
        pcls = pred[:, :, 5:]
        an = np.asarray(anchors, np.float32).reshape(-1, 2)[list(anchor_mask)]
        inp = Hc * downsample_ratio
        B = gb.shape[1]
        # target assignment (host-free, vectorized): each gt lands in one
        # cell + best anchor by wh-IoU
        gx = gb[:, :, 0] * Wc
        gy = gb[:, :, 1] * Hc
        gw = gb[:, :, 2] * inp
        gh = gb[:, :, 3] * inp
        valid = (gb[:, :, 2] > 0)
        ci = jnp.clip(gx.astype(jnp.int32), 0, Wc - 1)
        cj = jnp.clip(gy.astype(jnp.int32), 0, Hc - 1)
        inter = (jnp.minimum(gw[..., None], an[:, 0]) * jnp.minimum(gh[..., None], an[:, 1]))
        union = gw[..., None] * gh[..., None] + an[:, 0] * an[:, 1] - inter
        best_a = jnp.argmax(inter / (union + 1e-9), axis=-1)  # [N, B]
        smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0

        obj_t = jnp.zeros((N, A, Hc, Wc))
        loss = jnp.zeros((N,))
        bidx = jnp.arange(N)[:, None].repeat(B, 1)
        sc = gs if gs is not None else jnp.ones((N, B))
        tx = gx - jnp.floor(gx)
        ty = gy - jnp.floor(gy)
        tw = jnp.log(jnp.maximum(gw / an[best_a][..., 0], 1e-9))
        th = jnp.log(jnp.maximum(gh / an[best_a][..., 1], 1e-9))
        box_scale = 2.0 - gb[:, :, 2] * gb[:, :, 3]
        sel = lambda t: t[bidx, best_a, cj, ci]  # [N, B]
        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        mse = lambda a, b: jnp.abs(a - b)
        lx = bce(jnp.log(sel(px) / (1 - sel(px) + 1e-9) + 1e-9), tx) * box_scale
        ly = bce(jnp.log(sel(py) / (1 - sel(py) + 1e-9) + 1e-9), ty) * box_scale
        lw = mse(sel(pw), tw) * box_scale
        lh = mse(sel(phh), th) * box_scale
        pc = pcls[bidx, best_a, :, cj, ci]  # [N, B, class_num]
        tcls = jax.nn.one_hot(gl.reshape(N, B), class_num) * (1 - 2 * smooth) + smooth
        lc = bce(pc, tcls).sum(-1)
        per_gt = (lx + ly + lw + lh + lc) * valid * sc
        obj_t = obj_t.at[bidx, best_a, cj, ci].max(valid.astype(jnp.float32))
        lobj = bce(pobj, obj_t)
        # ignore mask (reference yolov3_loss_op.h CalcObjnessLoss): an
        # unassigned cell whose best decoded-box IoU over any gt exceeds
        # ignore_thresh contributes no objectness loss. The full IoU map is
        # [N,A,H,W,B] — small at YOLO head sizes (A=3, 13..52 grids).
        cellx = (jnp.arange(Wc) + px) / Wc
        celly = (jnp.arange(Hc)[:, None] + py) / Hc
        bw = jnp.exp(pw) * an[:, 0][None, :, None, None] / inp
        bh = jnp.exp(phh) * an[:, 1][None, :, None, None] / inp
        px1, py1 = cellx - bw / 2, celly - bh / 2
        px2, py2 = cellx + bw / 2, celly + bh / 2
        g1 = gb[:, :, :2] - gb[:, :, 2:4] / 2  # [N,B,2] corners
        g2 = gb[:, :, :2] + gb[:, :, 2:4] / 2
        gtb = lambda t: t[:, None, None, None, :]  # [N,B] -> broadcastable
        iw = jnp.maximum(jnp.minimum(px2[..., None], gtb(g2[:, :, 0]))
                         - jnp.maximum(px1[..., None], gtb(g1[:, :, 0])), 0.0)
        ih = jnp.maximum(jnp.minimum(py2[..., None], gtb(g2[:, :, 1]))
                         - jnp.maximum(py1[..., None], gtb(g1[:, :, 1])), 0.0)
        inter_p = iw * ih
        union_p = (bw * bh)[..., None] + gtb(gb[:, :, 2] * gb[:, :, 3]) - inter_p
        best_iou = jnp.max(inter_p / (union_p + 1e-9) * gtb(valid), axis=-1)
        keep = jnp.maximum(obj_t, (best_iou <= ignore_thresh).astype(lobj.dtype))
        loss = per_gt.sum(1) + (lobj * keep).sum((1, 2, 3))
        return loss

    args = [ensure_tensor(x), ensure_tensor(gt_box), ensure_tensor(gt_label)]
    if gt_score is not None:
        args.append(ensure_tensor(gt_score))
    return op(fn, *args, _name="yolo_loss")


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (reference read_file op)."""
    import jax.numpy as jnp

    from ..framework.core import _wrap_value

    data = np.fromfile(filename, dtype=np.uint8)
    return _wrap_value(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG bytes tensor -> [C, H, W] uint8 tensor (reference decode_jpeg;
    host-side via PIL — the reference decodes on CPU/nvjpeg)."""
    import io

    import jax.numpy as jnp
    from PIL import Image

    from ..framework.core import _wrap_value, unwrap

    raw = bytes(np.asarray(unwrap(ensure_tensor(x))).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return _wrap_value(jnp.asarray(arr))


class RoIAlign:
    """Layer form of roi_align (reference vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class DeformConv2D:
    """Layer form of deform_conv2d holding weight/bias (reference
    vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, deformable_groups=1, groups=1, weight_attr=None, bias_attr=None):
        import jax.numpy as jnp

        from ..framework.core import _wrap_value
        from ..framework.random import split_key

        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        import jax

        k = split_key()
        fan = in_channels * kh * kw
        self.weight = _wrap_value(jax.random.normal(k, (out_channels, in_channels, kh, kw),
                                                    jnp.float32) / np.sqrt(fan), stop_gradient=False)
        self.bias = None if bias_attr is False else _wrap_value(
            jnp.zeros((out_channels,), jnp.float32), stop_gradient=False)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation, self.deformable_groups,
                             self.groups, mask)


__all__ += ["psroi_pool", "deform_conv2d", "yolo_loss", "read_file", "decode_jpeg",
            "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D"]
