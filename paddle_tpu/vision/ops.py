"""Detection operators (parity: python/paddle/vision/ops.py — yolo_box,
roi_align, roi_pool, nms, deform_conv2d, ...).

TPU-first notes:
- roi_align / roi_pool: bilinear-gather formulations — static shapes, all
  gathers, XLA-fusable (no dynamic loops, unlike the CUDA kernels'
  per-box threads).
- nms: the sequential greedy suppression runs as a lax.fori_loop over a
  fixed box count — O(n²) IoU matrix + mask accumulation, compiled once;
  data-dependent survivor COUNT is resolved on the host at the end (the
  only inherently dynamic part).
- yolo_box: pure elementwise decode of the grid predictions.
"""
from __future__ import annotations

import numpy as np

from ..tensor._helpers import ensure_tensor, op

__all__ = ["nms", "roi_align", "roi_pool", "yolo_box"]


def _iou_matrix(boxes):
    import jax.numpy as jnp

    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None])
    iy1 = jnp.maximum(y1[:, None], y1[None])
    ix2 = jnp.minimum(x2[:, None], x2[None])
    iy2 = jnp.minimum(y2[:, None], y2[None])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None, top_k=None):
    """Greedy NMS (reference vision/ops.py:1395). Returns kept indices,
    sorted by descending score. With ``category_idxs``, suppression is
    per-category (multiclass NMS)."""
    import jax
    import jax.numpy as jnp

    boxes = ensure_tensor(boxes)
    n = int(boxes._value.shape[0])

    def kern(bv, sv, cv):
        order = jnp.argsort(-sv)
        bo = jnp.take(bv, order, axis=0)
        iou = _iou_matrix(bo)
        if cv is not None:
            co = jnp.take(cv, order)
            iou = jnp.where(co[:, None] == co[None], iou, 0.0)

        def body(i, keep):
            # keep box i iff no higher-scored KEPT box overlaps it
            sup = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
            return keep.at[i].set(~jnp.any(sup))

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        return keep, order

    sv = ensure_tensor(scores) if scores is not None else None
    cv = ensure_tensor(category_idxs) if category_idxs is not None else None

    def fn(bv, *rest):
        it = iter(rest)
        s = next(it) if sv is not None else jnp.zeros((n,), bv.dtype)
        c = next(it) if cv is not None else None
        return kern(bv, s, c)

    args = [boxes] + ([sv] if sv is not None else []) + ([cv] if cv is not None else [])
    keep_t, order_t = op(fn, *args, _name="nms")
    keep = np.asarray(keep_t.numpy())
    order = np.asarray(order_t.numpy())
    kept = order[keep]  # survivors in score order (host-side dynamic shape)
    if top_k is not None:
        kept = kept[: int(top_k)]
    from ..framework.core import _wrap_value
    import jax.numpy as jnp2

    return _wrap_value(jnp2.asarray(kept.astype(np.int64)))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference vision/ops.py:1181): bilinear sampling of each
    box on an output_size grid, averaged over sampling points."""
    import jax.numpy as jnp

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)

    def fn(feat, bxs, bnum):
        N, C, H, W = feat.shape
        n_boxes = bxs.shape[0]
        # map each box to its batch image from boxes_num (cumulative)
        bounds = jnp.cumsum(bnum)
        batch_idx = jnp.sum(jnp.arange(n_boxes)[:, None] >= bounds[None, :], axis=1)

        offset = 0.5 if aligned else 0.0
        xs1 = bxs[:, 0] * spatial_scale - offset
        ys1 = bxs[:, 1] * spatial_scale - offset
        xs2 = bxs[:, 2] * spatial_scale - offset
        ys2 = bxs[:, 3] * spatial_scale - offset
        bw = xs2 - xs1
        bh = ys2 - ys1
        if not aligned:
            bw = jnp.maximum(bw, 1.0)
            bh = jnp.maximum(bh, 1.0)

        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [n_boxes, ph*sr] y coords, [n_boxes, pw*sr] x coords
        gy = (jnp.arange(ph * sr) + 0.5) / sr  # in bin units
        gx = (jnp.arange(pw * sr) + 0.5) / sr
        ys = ys1[:, None] + bh[:, None] * gy[None] / ph
        xs = xs1[:, None] + bw[:, None] * gx[None] / pw

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [Py], xx [Px] -> [C, Py, Px]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1 = jnp.clip(y0 + 1, 0, H - 1)
            x1 = jnp.clip(x0 + 1, 0, W - 1)
            wy1 = jnp.clip(yy, 0, H - 1) - y0
            wx1 = jnp.clip(xx, 0, W - 1) - x0
            y0i, y1i, x0i, x1i = y0.astype(int), y1.astype(int), x0.astype(int), x1.astype(int)
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (1 - wy1)[None, :, None] * (1 - wx1)[None, None, :]
                    + v01 * (1 - wy1)[None, :, None] * wx1[None, None, :]
                    + v10 * wy1[None, :, None] * (1 - wx1)[None, None, :]
                    + v11 * wy1[None, :, None] * wx1[None, None, :])

        import jax

        def per_box(b):
            img = feat[batch_idx[b]]
            samp = bilinear(img, ys[b], xs[b])  # [C, ph*sr, pw*sr]
            return samp.reshape(C, ph, sr, pw, sr).mean(axis=(2, 4))

        return jax.vmap(per_box)(jnp.arange(n_boxes))

    return op(fn, x, boxes, boxes_num, _name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (max pooling per bin; reference vision/ops.py:1053) via a
    dense-sampled max (8 samples per bin edge approximates the exact
    integer-bin max with static shapes)."""
    import jax
    import jax.numpy as jnp

    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = ensure_tensor(x)
    boxes = ensure_tensor(boxes)
    boxes_num = ensure_tensor(boxes_num)

    def fn(feat, bxs, bnum):
        N, C, H, W = feat.shape
        n_boxes = bxs.shape[0]
        bounds = jnp.cumsum(bnum)
        batch_idx = jnp.sum(jnp.arange(n_boxes)[:, None] >= bounds[None, :], axis=1)
        sr = 8
        gy = jnp.arange(ph * sr) / sr
        gx = jnp.arange(pw * sr) / sr

        def per_box(b):
            img = feat[batch_idx[b]]
            x1 = bxs[b, 0] * spatial_scale
            y1 = bxs[b, 1] * spatial_scale
            x2 = jnp.maximum(bxs[b, 2] * spatial_scale, x1 + 1)
            y2 = jnp.maximum(bxs[b, 3] * spatial_scale, y1 + 1)
            ys = jnp.clip(jnp.round(y1 + (y2 - y1) * gy / ph), 0, H - 1).astype(int)
            xs = jnp.clip(jnp.round(x1 + (x2 - x1) * gx / pw), 0, W - 1).astype(int)
            samp = img[:, ys][:, :, xs]
            return samp.reshape(C, ph, sr, pw, sr).max(axis=(2, 4))

        return jax.vmap(per_box)(jnp.arange(n_boxes))

    return op(fn, x, boxes, boxes_num, _name="roi_pool")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio=32,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head predictions to boxes+scores (reference
    vision/ops.py:252). x: [N, A*(5+class_num), H, W]; returns
    (boxes [N, A*H*W, 4] in xyxy, scores [N, A*H*W, class_num])."""
    import jax.numpy as jnp

    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    x = ensure_tensor(x)
    img_size = ensure_tensor(img_size)

    def fn(xv, isz):
        N, _, H, W = xv.shape
        p = xv.reshape(N, A, 5 + class_num, H, W)
        cx = (jnp.arange(W))[None, None, None, :]
        cy = (jnp.arange(H))[None, None, :, None]
        sig = lambda v: 1 / (1 + jnp.exp(-v))
        bx = (sig(p[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1.0) + cx) / W
        by = (sig(p[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1.0) + cy) / H
        bw = jnp.exp(p[:, :, 2]) * anchors[None, :, 0, None, None] / (downsample_ratio * W)
        bh = jnp.exp(p[:, :, 3]) * anchors[None, :, 1, None, None] / (downsample_ratio * H)
        obj = sig(p[:, :, 4])
        cls = sig(p[:, :, 5:])
        score = obj[:, :, None] * cls  # [N, A, class, H, W]
        imgh = isz[:, 0].astype(jnp.float32)[:, None, None, None]
        imgw = isz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, A * H * W, 4)
        score = jnp.moveaxis(score, 2, -1).reshape(N, A * H * W, class_num)
        keep = (obj.reshape(N, A * H * W) > conf_thresh)[..., None]
        return boxes * keep, score * keep

    import jax

    return op(fn, x, img_size, _name="yolo_box")
