"""ShuffleNetV2 (parity: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn
from ...tensor.manipulation import concat, flatten, reshape, transpose

_CFGS = {
    "0.5": ([4, 8, 4], [24, 48, 96, 192, 1024]),
    "1.0": ([4, 8, 4], [24, 116, 232, 464, 1024]),
    "1.5": ([4, 8, 4], [24, 176, 352, 704, 1024]),
    "2.0": ([4, 8, 4], [24, 244, 488, 976, 2048]),
}


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


def _dw_bn(in_c, out_c, kernel, stride):
    return nn.Sequential(
        nn.Conv2D(in_c, in_c, kernel, stride=stride, padding=kernel // 2, groups=in_c, bias_attr=False),
        nn.BatchNorm2D(in_c),
        nn.Conv2D(in_c, out_c, 1, bias_attr=False),
        nn.BatchNorm2D(out_c),
        nn.ReLU(),
    )


def _pw_bn_relu(in_c, out_c):
    return nn.Sequential(nn.Conv2D(in_c, out_c, 1, bias_attr=False), nn.BatchNorm2D(out_c), nn.ReLU())


class ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(_pw_bn_relu(in_c // 2, branch_c), _dw_bn(branch_c, branch_c, 3, 1))
        else:
            self.branch1 = _dw_bn(in_c, in_c, 3, stride)
            self.branch2 = nn.Sequential(_pw_bn_relu(in_c, branch_c), _dw_bn(branch_c, branch_c, 3, stride))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        stages, chans = _CFGS[str(scale)]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(nn.Conv2D(3, chans[0], 3, stride=2, padding=1, bias_attr=False), nn.BatchNorm2D(chans[0]), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        blocks = []
        in_c = chans[0]
        for i, reps in enumerate(stages):
            out_c = chans[i + 1]
            blocks.append(ShuffleUnit(in_c, out_c, 2))
            for _ in range(reps - 1):
                blocks.append(ShuffleUnit(out_c, out_c, 1))
            in_c = out_c
        self.features = nn.Sequential(*blocks)
        self.conv_last = _pw_bn_relu(in_c, chans[-1])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.features(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    kwargs.pop("pretrained", None)
    return ShuffleNetV2("0.5", **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    kwargs.pop("pretrained", None)
    return ShuffleNetV2("1.0", **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    kwargs.pop("pretrained", None)
    return ShuffleNetV2("1.5", **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    kwargs.pop("pretrained", None)
    return ShuffleNetV2("2.0", **kwargs)
