"""Vision transforms (parity: python/paddle/vision/transforms) — numpy host
pipeline (the device never sees per-sample python code)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class ToTensor:
    def __init__(self, data_format="CHW"):
        pass

    def __call__(self, img):
        raw = np.asarray(img)
        # scale decision keyed on the input dtype, not the values, so every
        # sample in a uint8 dataset gets the same normalization
        scale = 255.0 if raw.dtype == np.uint8 else 1.0
        arr = raw.astype(np.float32)
        if arr.ndim == 3 and arr.shape[-1] in (1, 3):
            arr = arr.transpose(2, 0, 1)
        return arr / scale


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(img[..., ::-1])
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        c, h, w = img.shape
        if self.padding:
            img = np.pad(img, [(0, 0), (self.padding, self.padding), (self.padding, self.padding)])
            h, w = img.shape[1:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i : i + th, j : j + tw]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        # nearest resize on host (cheap); models needing quality resize do it
        # on device via F.interpolate
        c, h, w = img.shape
        th, tw = self.size
        yi = (np.arange(th) * h // th).clip(0, h - 1)
        xi = (np.arange(tw) * w // tw).clip(0, w - 1)
        return img[:, yi][:, :, xi]
