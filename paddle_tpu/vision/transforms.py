"""Vision transforms (parity: python/paddle/vision/transforms) — numpy host
pipeline (the device never sees per-sample python code)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class ToTensor:
    def __init__(self, data_format="CHW"):
        pass

    def __call__(self, img):
        raw = np.asarray(img)
        # scale decision keyed on the input dtype, not the values, so every
        # sample in a uint8 dataset gets the same normalization
        scale = 255.0 if raw.dtype == np.uint8 else 1.0
        arr = raw.astype(np.float32)
        if arr.ndim == 3 and arr.shape[-1] in (1, 3):
            arr = arr.transpose(2, 0, 1)
        return arr / scale


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(img[..., ::-1])
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        c, h, w = img.shape
        if self.padding:
            img = np.pad(img, [(0, 0), (self.padding, self.padding), (self.padding, self.padding)])
            h, w = img.shape[1:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[:, i : i + th, j : j + tw]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        # nearest resize on host (cheap); models needing quality resize do it
        # on device via F.interpolate
        c, h, w = img.shape
        th, tw = self.size
        yi = (np.arange(th) * h // th).clip(0, h - 1)
        xi = (np.arange(tw) * w // tw).clip(0, w - 1)
        return img[:, yi][:, :, xi]


# -- round-4 transform tail (reference vision/transforms/transforms.py) ------
# Built on the HWC/CHW-agnostic functionals in vision/functional.py.

from . import functional as Fv  # noqa: E402
from .functional import (  # noqa: E402,F401 — functional forms live here too
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    affine,
    center_crop,
    crop,
    erase,
    hflip,
    normalize,
    pad,
    perspective,
    resize,
    rotate,
    to_grayscale,
    to_tensor,
    vflip,
)


def _img_hw(img):
    """(h, w) of an image in this pipeline's conventions: Tensors are CHW
    (the vision/functional.py contract, any channel count); ndarrays are HWC
    unless the leading axis looks like 1/3 channels."""
    from ..framework.core import Tensor

    if isinstance(img, Tensor):
        sh = tuple(img.shape)
        return int(sh[-2]), int(sh[-1])
    arr = np.asarray(img)
    if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3):
        return arr.shape[1], arr.shape[2]
    return arr.shape[0], arr.shape[1]


class BaseTransform:
    """Transform base (reference BaseTransform): keys select which inputs
    get transformed; single-image transforms just implement _apply_image."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            out = [self._apply_image(v) if k == "image" else v
                   for k, v in zip(self.keys, inputs)]
            out.extend(inputs[len(self.keys):])  # extras pass through
            return tuple(out)
        return self._apply_image(inputs)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return Fv.center_crop(img, self.size)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return Fv.pad(img, self.padding, self.fill, self.mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        return Fv.to_grayscale(img, self.n)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return Fv.adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness), ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        for t in np.random.permutation(self.ts):
            img = t._apply_image(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return Fv.vflip(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else tuple(degrees)
        self.kw = dict(interpolation=interpolation, expand=expand, center=center, fill=fill)

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return Fv.rotate(img, angle, **self.kw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None, interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else tuple(degrees)
        self.translate, self.scale_rng, self.shear = translate, scale, shear
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def _apply_image(self, img):
        h, w = _img_hw(img)
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        if self.shear is None:
            sh = (0, 0)
        elif np.isscalar(self.shear):
            sh = (np.random.uniform(-self.shear, self.shear), 0)
        else:  # sequence form: (min, max) x-shear, or (xmin, xmax, ymin, ymax)
            s = tuple(self.shear)
            sh = (np.random.uniform(s[0], s[1]),
                  np.random.uniform(s[2], s[3]) if len(s) == 4 else 0)
        return Fv.affine(img, angle=angle, translate=(tx, ty), scale=sc, shear=sh,
                         interpolation=self.interpolation, center=self.center, fill=self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.d = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = _img_hw(img)
        dx, dy = self.d * w / 2, self.d * h / 2
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.uniform(0, dx), np.random.uniform(0, dy)),
               (w - 1 - np.random.uniform(0, dx), np.random.uniform(0, dy)),
               (w - 1 - np.random.uniform(0, dx), h - 1 - np.random.uniform(0, dy)),
               (np.random.uniform(0, dx), h - 1 - np.random.uniform(0, dy))]
        return Fv.perspective(img, start, end, self.interpolation, self.fill)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio, self.interpolation = scale, ratio, interpolation

    def _apply_image(self, img):
        h, w = _img_hw(img)
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                return Fv.resize(Fv.crop(img, top, left, ch, cw), self.size, self.interpolation)
        return Fv.resize(Fv.center_crop(img, min(h, w)), self.size, self.interpolation)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3), value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        h, w = _img_hw(img)
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return Fv.erase(img, i, j, eh, ew, self.value, self.inplace)
        return img
