"""paddle_tpu.fft — discrete Fourier transforms.

Parity: ``paddle.fft`` (reference python/paddle/fft.py — 1d/2d/nd c2c, r2c,
c2r transforms + helpers, backed by cuFFT kernels in
paddle/phi/kernels/gpu/fft_kernel.cu). TPU-first: jnp.fft lowers to XLA's FFT
HLO; each op routes through ``primitive`` so it is tape-differentiable, jit
traceable, and static-capturable like every other tensor op.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .tensor._helpers import ensure_tensor, op


def _norm(norm):
    if norm in (None, "backward", "forward", "ortho"):
        return norm or "backward"
    raise ValueError(f"norm must be 'forward'/'backward'/'ortho', got {norm!r}")


def _c2c(jfn, x, n, axis, norm, name):
    return op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)), ensure_tensor(x), _name=name)


def _c2c_nd(jfn, x, s, axes, norm, name):
    return op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)), ensure_tensor(x), _name=name)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _c2c(jnp.fft.fft, x, n, axis, norm, "fft")


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _c2c(jnp.fft.ifft, x, n, axis, norm, "ifft")


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _c2c(jnp.fft.rfft, x, n, axis, norm, "rfft")


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _c2c(jnp.fft.irfft, x, n, axis, norm, "irfft")


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _c2c(jnp.fft.hfft, x, n, axis, norm, "hfft")


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _c2c(jnp.fft.ihfft, x, n, axis, norm, "ihfft")


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _c2c_nd(jnp.fft.fft2, x, s, axes, norm, "fft2")


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _c2c_nd(jnp.fft.ifft2, x, s, axes, norm, "ifft2")


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _c2c_nd(jnp.fft.rfft2, x, s, axes, norm, "rfft2")


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _c2c_nd(jnp.fft.irfft2, x, s, axes, norm, "irfft2")


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _c2c_nd(jnp.fft.fftn, x, s, axes, norm, "fftn")


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _c2c_nd(jnp.fft.ifftn, x, s, axes, norm, "ifftn")


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _c2c_nd(jnp.fft.rfftn, x, s, axes, norm, "rfftn")


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _c2c_nd(jnp.fft.irfftn, x, s, axes, norm, "irfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    # jnp has no hfft2; express via irfftn on the conjugate (standard identity)
    return op(lambda v: jnp.fft.irfftn(jnp.conj(v), s=s, axes=axes, norm=_norm(norm)),
              ensure_tensor(x), _name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return op(lambda v: jnp.conj(jnp.fft.rfftn(v, s=s, axes=axes, norm=_norm(norm))),
              ensure_tensor(x), _name="ihfft2")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import _wrap_value

    return _wrap_value(jnp.fft.fftfreq(int(n), d=float(d)))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import _wrap_value

    return _wrap_value(jnp.fft.rfftfreq(int(n), d=float(d)))


def fftshift(x, axes=None, name=None):
    return op(lambda v: jnp.fft.fftshift(v, axes=axes), ensure_tensor(x), _name="fftshift")


def ifftshift(x, axes=None, name=None):
    return op(lambda v: jnp.fft.ifftshift(v, axes=axes), ensure_tensor(x), _name="ifftshift")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-d Hermitian FFT (reference paddle.fft.hfftn). jnp has no hfftn;
    identity: hfftn(x, norm) = irfftn(conj(x), s, backward) * prod(S) with
    the requested norm applied as an explicit scale."""

    def fn(v):
        if axes is not None:
            ax = tuple(axes)
        else:  # numpy semantics: s picks the LAST len(s) axes
            ax = tuple(range(v.ndim))[-len(s):] if s is not None else tuple(range(v.ndim))
        if s is None:
            shape = [v.shape[a] for a in ax]
            shape[-1] = max(2 * (v.shape[ax[-1]] - 1), 1)
        else:
            shape = list(s)
        N = float(np.prod(shape))
        scale = {"backward": 1.0, "ortho": 1.0 / np.sqrt(N), "forward": 1.0 / N}[norm]
        return jnp.fft.irfftn(jnp.conj(v), s=shape, axes=ax, norm="backward") * N * scale

    return op(fn, ensure_tensor(x), _name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn: conj(rfftn(x, backward)) with the inverse scale."""

    def fn(v):
        if axes is not None:
            ax = tuple(axes)
        else:
            ax = tuple(range(v.ndim))[-len(s):] if s is not None else tuple(range(v.ndim))
        shape = list(s) if s is not None else [v.shape[a] for a in ax]
        N = float(np.prod(shape))
        scale = {"backward": 1.0 / N, "ortho": 1.0 / np.sqrt(N), "forward": 1.0}[norm]
        return jnp.conj(jnp.fft.rfftn(v, s=shape, axes=ax, norm="backward")) * scale

    return op(fn, ensure_tensor(x), _name="ihfftn")
