"""Optimizer classes (parity: python/paddle/optimizer/optimizer.py:91).

Eager: ``opt.step()`` reads param.grad tensors, runs the functional core
once over the whole param pytree, writes params in place. Jit: the same core
is consumed by ``paddle_tpu.jit.TrainStep`` so forward+backward+update is a
single XLA computation (the reference's minimize() emits per-param update
ops, optimizer.py:1165 — here XLA fuses the lot).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, _wrap_value
from ..nn.clip import ClipGradBase
from . import functional as Fopt
from .lr import LRScheduler


class Optimizer:
    _core_cls = Fopt.SGDCore

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None, core=None, multi_precision=False):
        self._lr = learning_rate
        self._params: List[Tensor] = list(parameters) if parameters is not None else []
        self._grad_clip: Optional[ClipGradBase] = grad_clip
        # weight_decay may be a float or a regularizer.L1Decay/L2Decay object
        from ..regularizer import L1Decay

        self._wd_is_l1 = isinstance(weight_decay, L1Decay)
        self._weight_decay = float(weight_decay) if weight_decay is not None else None
        self.core = core if core is not None else self._core_cls()
        self._state = None
        self._step_count = 0

    # -- lr ---------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        self._lr = value

    def lr_at(self, step):
        """Traced LR for jit steps."""
        if isinstance(self._lr, LRScheduler):
            return self._lr.lr_at(step)
        return jnp.asarray(self._lr, jnp.float32)

    @property
    def _learning_rate(self):
        return self._lr

    # -- eager path --------------------------------------------------------
    def _ensure_state(self, params_tree):
        if self._state is None:
            self._state = self.core.init(params_tree)

    def step(self):
        from ..framework.selected_rows import take_pending_rows

        params = [p for p in self._params if not p.stop_gradient]
        grads = [p.grad for p in params]
        if self._grad_clip is not None:
            tree = {i: g._value for i, g in enumerate(grads) if g is not None}
            clipped = self._grad_clip.apply_tree(tree)
            for i, g in enumerate(grads):
                if g is not None:
                    g._value = clipped[i]
        # row-sparse params (Embedding(sparse=True) recorded touched rows):
        # lazy cores update only those rows — O(batch) not O(vocab). Global
        # grad clip already densified everything, so it disables laziness.
        lazy = getattr(self, "_lazy_sparse", False) and hasattr(self.core, "row_update") \
            and self._grad_clip is None and not self._weight_decay
        sparse: Dict[int, object] = {}
        for i, p in enumerate(params):
            rows = take_pending_rows(p)  # always drain — stale rows must not leak
            if rows is not None and lazy and grads[i] is not None:
                sparse[i] = rows
        ptree = {i: p._value for i, p in enumerate(params) if grads[i] is not None and i not in sparse}
        gtree = {i: grads[i]._value for i in ptree}
        self._pre_update(params, ptree)
        if self._weight_decay and not isinstance(self, _DecoupledWD):
            # L1/L2 regularization: grad += wd * (sign(p) | p) (reference
            # regularizer.py L1Decay/L2Decay)
            pen = (lambda p: jnp.sign(p)) if self._wd_is_l1 else (lambda p: p)
            gtree = {i: g + self._weight_decay * pen(ptree[i]) for i, g in gtree.items()}
        self._ensure_state({i: p._value for i, p in enumerate(params)})
        new_params, new_state = self._apply(gtree, ptree)
        for i, p in enumerate(params):
            if i in new_params:
                p._apply_update(new_params[i])
        lr = self.get_lr()
        for i, rows in sparse.items():
            p = params[i]
            rows_j = jnp.asarray(rows, jnp.int32)
            state_p = {k: self._state[k][i] for k in self._state} if self._state else {}
            new_p, new_state_p = self.core.row_update(
                rows_j, grads[i]._value[rows_j], state_p, p._value, lr, self._step_count)
            p._apply_update(new_p)
            for k, v in new_state_p.items():
                self._state[k][i] = v
        self._step_count += 1

    def _pre_update(self, params, ptree):
        """Subclass hook run after grad filtering, before the core update."""

    def _traced_update(self, gtree, opt_state, ptree, step):
        """Grad → new-param transform shared by every compiled path (jit
        TrainStep, static Executor): weight decay, clip, lr schedule, core
        update. One definition so the training semantics cannot diverge."""
        if self._weight_decay:
            pen = (lambda p: jnp.sign(p)) if self._wd_is_l1 else (lambda p: p)
            gtree = jax.tree_util.tree_map(lambda g, p: g + self._weight_decay * pen(p), gtree, ptree)
        if self._grad_clip is not None:
            gtree = self._grad_clip.apply_tree(gtree)
        lr = self.lr_at(step)
        new_params, new_opt = self.core.update(gtree, opt_state, ptree, lr, step)
        return new_params, new_opt, lr

    def _apply(self, gtree, ptree):
        lr = self.get_lr()
        state_sub = {k: {i: v[i] for i in ptree} for k, v in self._state.items()} if self._state else {}
        new_params, new_sub = self.core.update(gtree, state_sub, ptree, lr, self._step_count)
        for k in new_sub:
            self._state[k].update(new_sub[k])
        return new_params, self._state

    def clear_grad(self, set_to_zero=True):
        from ..framework.selected_rows import take_pending_rows

        for p in self._params:
            p.grad = None
            take_pending_rows(p)  # drop any rows recorded without a step

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..framework.static_trace import current_program, is_symbolic

        if isinstance(loss, Tensor) and is_symbolic(loss._value):
            # static mode (reference optimizer.py:1165 emits backward + update
            # ops into the program; here Executor.run fuses them into the jit)
            from ..static import append_backward, default_main_program

            prog = current_program() or default_main_program()
            params = parameters or self._params or None
            params_grads = append_backward(loss, parameter_list=params)
            if not self._params:
                self._params = [p for p, _ in params_grads]
            prog.optimizer = self
            return None, params_grads
        loss.backward()
        self.step()
        return None, None

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        out = {"step": self._step_count}
        if self._state:
            for k, tree in self._state.items():
                for i, v in tree.items():
                    out[f"{k}.{i}"] = _wrap_value(v)
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("step", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])
        groups: Dict[str, dict] = {}
        for key, v in state.items():
            if key in ("step", "LR_Scheduler"):
                continue
            k, i = key.rsplit(".", 1)
            groups.setdefault(k, {})[int(i)] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
        if groups:
            self._state = groups


class _DecoupledWD:
    pass


class SGD(Optimizer):
    _core_cls = Fopt.SGDCore
    # SGD over a row-sparse grad touches only those rows — identical to the
    # dense result, so laziness is always safe (reference sgd_op SelectedRows)
    _lazy_sparse = True


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False, weight_decay=None, grad_clip=None, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, core=Fopt.MomentumCore(momentum, use_nesterov))


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False, name=None, multi_precision=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, core=Fopt.AdamCore(beta1, beta2, epsilon))
        # lazy_mode: row-sparse moment/param updates for Embedding(sparse=True)
        # grads (reference adam_op.h lazy_mode branch)
        self._lazy_sparse = bool(lazy_mode)


class RowSparseAdam(Optimizer):
    """Adam with a row-sparse (lazy) traced update for the params named in
    ``sparse_params`` — the recsys per-step partial embedding update: only
    rows the batch looked up change (params and moments; unseen rows stay
    bitwise), O(touched rows) semantics over a table whose vocab dwarfs any
    batch. Eager mode inherits the ``Adam(lazy_mode=True)`` SelectedRows
    path (``ShardedEmbedding``/``Embedding(sparse=True)`` record touched
    rows). ``sparse_params`` uses TrainStep state keys — the model's
    ``named_parameters`` names, e.g. ``DLRM.sparse_param_names()``.

    ``weight_decay`` is rejected: decay touches every row every step, which
    contradicts the lazy contract (use AdamW on the dense params instead).
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, sparse_params=(),
                 weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        if weight_decay:
            raise ValueError(
                "RowSparseAdam does not support weight_decay: decay writes "
                "every table row every step, defeating the row-sparse "
                "update contract")
        super().__init__(learning_rate, parameters, None, grad_clip,
                         core=Fopt.RowSparseAdamCore(beta1, beta2, epsilon,
                                                     sparse=sparse_params))
        self._lazy_sparse = True


class AdamW(Optimizer, _DecoupledWD):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=0.01, apply_decay_param_fun=None, grad_clip=None, lr_ratio=None, name=None, multi_precision=False):
        self.apply_decay_param_fun = apply_decay_param_fun
        super().__init__(learning_rate, parameters, None, grad_clip, core=Fopt.AdamWCore(beta1, beta2, epsilon, float(weight_decay)))

    def _pre_update(self, params, ptree):
        # decay mask honoring apply_decay_param_fun (paddle parity) — keyed
        # exactly like the update tree (grads-present params only)
        if self.apply_decay_param_fun is not None:
            self.core.decay_mask = {
                i: 1.0 if self.apply_decay_param_fun(params[i].name or str(i)) else 0.0 for i in ptree
            }


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, core=Fopt.LambCore(beta1, beta2, epsilon, lamb_weight_decay))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None, grad_clip=None, name=None, initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, core=Fopt.AdagradCore(epsilon, initial_accumulator_value))


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, core=Fopt.RMSPropCore(rho, epsilon, momentum, centered))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, core=Fopt.AdadeltaCore(rho, epsilon))


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, core=Fopt.AdamaxCore(beta1, beta2, epsilon))
