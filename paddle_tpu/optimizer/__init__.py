"""paddle_tpu.optimizer (parity: python/paddle/optimizer)."""
from . import functional  # noqa: F401
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Momentum,
    Optimizer,
    RMSProp,
    RowSparseAdam,
)
