"""LR schedulers (parity: python/paddle/optimizer/lr.py).

Dual-form like everything else: stateful ``get_lr()/step()`` for eager, and
``lr_at(step)`` — a pure function of the step counter — consumed inside the
compiled train step (no host round-trip per step).
"""
from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.last_lr = learning_rate
        self.verbose = verbose
        self.step()

    def get_lr(self):
        raise NotImplementedError

    def lr_at(self, step):
        """Pure function of step (traced-friendly). Default: host fallback."""
        raise NotImplementedError

    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: lr set to {self.last_lr}")

    def __call__(self):
        return self.last_lr

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]
        self.last_lr = state["last_lr"]


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = max(self.last_epoch, 1)
        return self.base_lr * self.d_model**-0.5 * min(t**-0.5, t * self.warmup_steps**-1.5)

    def lr_at(self, step):
        t = jnp.maximum(step, 1).astype(jnp.float32)
        return self.base_lr * self.d_model**-0.5 * jnp.minimum(t**-0.5, t * self.warmup_steps**-1.5)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries, self.values = list(boundaries), list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]

    def lr_at(self, step):
        lr = jnp.asarray(self.values[len(self.boundaries)], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            lr = jnp.where(step < b, v, lr)
        return lr


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)

    def lr_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * step.astype(jnp.float32))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)

    def lr_at(self, step):
        return self.base_lr / (1 + self.gamma * step.astype(jnp.float32))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0, cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps, self.end_lr, self.power, self.cycle = decay_steps, end_lr, power, cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        if self.cycle:
            div = max(1.0, math.ceil(t / self.decay_steps))
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            t = min(t, decay_steps)
        return (self.base_lr - self.end_lr) * (1 - t / decay_steps) ** self.power + self.end_lr

    def lr_at(self, step):
        t = step.astype(jnp.float32)
        if self.cycle:
            div = jnp.maximum(1.0, jnp.ceil(t / self.decay_steps))
            ds = self.decay_steps * div
        else:
            ds = jnp.asarray(float(self.decay_steps))
            t = jnp.minimum(t, ds)
        return (self.base_lr - self.end_lr) * (1 - t / ds) ** self.power + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.after_lr = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps, self.start_lr, self.end_lr = warmup_steps, start_lr, end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        if t < self.warmup_steps:
            return (self.end_lr - self.start_lr) * t / self.warmup_steps + self.start_lr
        if self.lr_sched is not None:
            self.lr_sched.last_epoch = t - self.warmup_steps
            return self.lr_sched.get_lr()
        return self.after_lr

    def lr_at(self, step):
        t = step.astype(jnp.float32)
        warm = (self.end_lr - self.start_lr) * t / self.warmup_steps + self.start_lr
        if self.lr_sched is not None:
            after = self.lr_sched.lr_at(step - self.warmup_steps)
        else:
            after = jnp.asarray(self.after_lr, jnp.float32)
        return jnp.where(step < self.warmup_steps, warm, after)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma**self.last_epoch

    def lr_at(self, step):
        return self.base_lr * self.gamma ** step.astype(jnp.float32)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma**n

    def lr_at(self, step):
        n = sum(jnp.where(step >= m, 1, 0) for m in self.milestones)
        return self.base_lr * self.gamma ** n.astype(jnp.float32)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)

    def lr_at(self, step):
        return self.base_lr * self.gamma ** (step // self.step_size).astype(jnp.float32)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def lr_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2

    def lr_at(self, step):
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + jnp.cos(jnp.pi * step.astype(jnp.float32) / self.T_max)) / 2


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0, end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos", three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.up_steps = int(phase_pct * total_steps)
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self):
        t = self.last_epoch
        if t <= self.up_steps:
            pct = t / max(self.up_steps, 1)
            return self.initial_lr + (self.max_lr - self.initial_lr) * (1 - math.cos(math.pi * pct)) / 2
        pct = (t - self.up_steps) / max(self.total_steps - self.up_steps, 1)
        return self.end_lr + (self.max_lr - self.end_lr) * (1 + math.cos(math.pi * min(pct, 1.0))) / 2

    def lr_at(self, step):
        t = step.astype(jnp.float32)
        up = self.initial_lr + (self.max_lr - self.initial_lr) * (1 - jnp.cos(jnp.pi * t / max(self.up_steps, 1))) / 2
        pct = jnp.minimum((t - self.up_steps) / max(self.total_steps - self.up_steps, 1), 1.0)
        down = self.end_lr + (self.max_lr - self.end_lr) * (1 + jnp.cos(jnp.pi * pct)) / 2
        return jnp.where(step <= self.up_steps, up, down)


class ReduceOnPlateau(LRScheduler):
    """Host-driven (metric-dependent) — eager/fit loop only."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10, threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0, epsilon=1e-8, verbose=False):
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr = cooldown, min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = learning_rate
        self.last_lr = learning_rate
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        cur = float(metrics.item() if hasattr(metrics, "item") else metrics)
        better = self.best is None or (cur < self.best - self.threshold if self.mode == "min" else cur > self.best + self.threshold)
        if better:
            self.best = cur
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        elif self.num_bad > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0


class MultiplicativeDecay(LRScheduler):
    """lr_{t} = lr_{t-1} * lr_lambda(t) (reference optimizer/lr.py
    MultiplicativeDecay). Stateful product — lr_at(step) recomputes the
    prefix product for traced use (host loop; schedulers run per epoch)."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        lr = self.base_lr
        for t in range(1, self.last_epoch + 1):
            lr *= self.lr_lambda(t)
        return lr

    def lr_at(self, step):
        import jax.numpy as jnp

        try:
            s = int(step)
        except TypeError:
            raise NotImplementedError(
                "MultiplicativeDecay needs a concrete step under tracing; "
                "drive it per-epoch via scheduler.step()")
        return jnp.asarray(self.get_lr() if s == self.last_epoch else
                           self.base_lr * float(np.prod([self.lr_lambda(t) for t in range(1, s + 1)])),
                           jnp.float32)


class CyclicLR(LRScheduler):
    """Triangular cyclic schedule (reference optimizer/lr.py CyclicLR):
    cycles between base_learning_rate and max_learning_rate with
    step_size_up/down, scaled per mode."""

    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0,
                 scale_fn=None, scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = int(step_size_up)
        self.down = int(step_size_down) if step_size_down is not None else self.up
        self.mode = mode
        self.gamma = exp_gamma
        if scale_fn is not None:
            self.scale_fn, self.scale_mode = scale_fn, scale_mode
        elif mode == "triangular":
            self.scale_fn, self.scale_mode = (lambda c: 1.0), "cycle"
        elif mode == "triangular2":
            self.scale_fn, self.scale_mode = (lambda c: 1.0 / (2.0 ** (c - 1))), "cycle"
        elif mode == "exp_range":
            self.scale_fn, self.scale_mode = (lambda it: self.gamma ** it), "iterations"
        else:
            raise ValueError(mode)
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        return float(self.lr_at(max(self.last_epoch, 0)))

    def lr_at(self, step):
        # jnp ops so this traces inside compiled train steps (lr_at contract)
        total = self.up + self.down
        stepf = jnp.asarray(step, jnp.float32)
        cycle = jnp.floor(1 + stepf / total)
        it = stepf - (cycle - 1) * total
        x = jnp.where(it <= self.up, it / self.up, 1.0 - (it - self.up) / self.down)
        scale = self.scale_fn(cycle if self.scale_mode == "cycle" else stepf)
        return (self.base_lr + (self.max_lr - self.base_lr) * x * scale).astype(jnp.float32)
