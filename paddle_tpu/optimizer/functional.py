"""Functional optimizer cores: ``init(params) -> state``,
``update(grads, state, params, lr) -> (new_params, new_state)``.

Parity: the per-param optimizer kernels in
paddle/fluid/operators/optimizers/*.cc (sgd/momentum/adam/adamw/lamb/...).
TPU-first: one pytree-wide update compiled into the train step — XLA fuses
the whole update into a handful of elementwise kernels; no per-param op
dispatch (reference `_append_optimize_op`,
python/paddle/optimizer/optimizer.py:559).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


def _zeros_like_tree(params):
    return tmap(jnp.zeros_like, params)


class SGDCore:
    def init(self, params):
        return {}

    def update(self, grads, state, params, lr, step):
        new_params = tmap(lambda p, g: p - lr * g.astype(p.dtype), params, grads)
        return new_params, state

    def row_update(self, rows, g_rows, state_p, p, lr, step):
        """Row-sparse update (reference selected_rows sgd kernel): only the
        embedding rows touched this step change."""
        return p.at[rows].add((-lr * g_rows).astype(p.dtype)), state_p


class MomentumCore:
    def __init__(self, momentum=0.9, use_nesterov=False):
        self.mu = momentum
        self.nesterov = use_nesterov

    def init(self, params):
        return {"velocity": _zeros_like_tree(params)}

    def update(self, grads, state, params, lr, step):
        vel = tmap(lambda v, g: self.mu * v + g, state["velocity"], grads)
        if self.nesterov:
            new_params = tmap(lambda p, g, v: p - lr * (g + self.mu * v), params, grads, vel)
        else:
            new_params = tmap(lambda p, v: p - lr * v, params, vel)
        return new_params, {"velocity": vel}


class AdamCore:
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def init(self, params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def _moments(self, grads, state):
        m = tmap(lambda m, g: self.b1 * m + (1 - self.b1) * g.astype(m.dtype), state["m"], grads)
        v = tmap(lambda v, g: self.b2 * v + (1 - self.b2) * jnp.square(g.astype(v.dtype)), state["v"], grads)
        return m, v

    def update(self, grads, state, params, lr, step):
        m, v = self._moments(grads, state)
        t = step + 1
        bc1 = 1 - self.b1**t
        bc2 = 1 - self.b2**t
        new_params = tmap(
            lambda p, mm, vv: p - (lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps)).astype(p.dtype),
            params, m, v,
        )
        return new_params, {"m": m, "v": v}

    def row_update(self, rows, g_rows, state_p, p, lr, step):
        """Lazy-mode row-sparse Adam (reference adam_op.h lazy_mode branch):
        moments and params update only on the rows present this step; unseen
        rows keep their moments (no decay), exactly the reference contract."""
        m, v = state_p["m"], state_p["v"]
        g = g_rows.astype(m.dtype)
        m_r = self.b1 * m[rows] + (1 - self.b1) * g
        v_r = self.b2 * v[rows] + (1 - self.b2) * jnp.square(g)
        t = step + 1
        bc1 = 1 - self.b1**t
        bc2 = 1 - self.b2**t
        new_p = p.at[rows].add(-(lr * (m_r / bc1) / (jnp.sqrt(v_r / bc2) + self.eps)).astype(p.dtype))
        return new_p, {"m": m.at[rows].set(m_r), "v": v.at[rows].set(v_r)}


class RowSparseAdamCore(AdamCore):
    """Adam whose traced update is row-sparse (lazy) for named embedding
    tables: rows with an all-zero gradient this step keep their parameters
    AND moments bitwise (no moment decay on unseen rows) — the
    ``adam_op.h lazy_mode`` contract extended into compiled code, matching
    the eager :meth:`AdamCore.row_update` path. Under a looked-up-rows
    producer (``ShardedEmbedding``'s custom_vjp scatter-adds only touched
    rows), the nonzero-grad row set IS the looked-up row set. The masked
    update is elementwise over the row dim, so a row-sharded table updates
    shard-locally with no extra collectives.

    ``sparse`` names the state-tree param keys treated lazily (e.g.
    ``DLRM.sparse_param_names()``); every other param takes the ordinary
    dense Adam step.
    """

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, sparse=()):
        super().__init__(beta1, beta2, epsilon)
        self.sparse = frozenset(sparse)

    def _row_update(self, g, m, v, p, lr, step):
        touched = jnp.any(g != 0, axis=tuple(range(1, g.ndim)), keepdims=True)
        g = g.astype(m.dtype)
        m_new = jnp.where(touched, self.b1 * m + (1 - self.b1) * g, m)
        v_new = jnp.where(touched, self.b2 * v + (1 - self.b2) * jnp.square(g), v)
        t = step + 1
        bc1 = 1 - self.b1**t
        bc2 = 1 - self.b2**t
        upd = (lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)).astype(p.dtype)
        return jnp.where(touched, p - upd, p), m_new, v_new

    def update(self, grads, state, params, lr, step):
        sparse = self.sparse & set(params) if isinstance(params, dict) else frozenset()
        if not sparse:
            return super().update(grads, state, params, lr, step)
        dense = set(params) - sparse
        sub = lambda tree, ks: {k: tree[k] for k in ks}  # noqa: E731
        new_p, new_st = super().update(
            sub(grads, dense),
            {"m": sub(state["m"], dense), "v": sub(state["v"], dense)},
            sub(params, dense), lr, step)
        new_m, new_v = dict(new_st["m"]), dict(new_st["v"])
        new_p = dict(new_p)
        for k in sparse:
            new_p[k], new_m[k], new_v[k] = self._row_update(
                grads[k], state["m"][k], state["v"][k], params[k], lr, step)
        return new_p, {"m": new_m, "v": new_v}


class AdamWCore(AdamCore):
    """Decoupled weight decay (reference: operators/optimizers/adamw_op). The
    ``apply_decay_fn`` predicate mirrors paddle's apply_decay_param_fun."""

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, weight_decay=0.01, decay_mask=None):
        super().__init__(beta1, beta2, epsilon)
        self.wd = weight_decay
        self.decay_mask = decay_mask  # pytree of bools matching params, or None

    def update(self, grads, state, params, lr, step):
        m, v = self._moments(grads, state)
        t = step + 1
        bc1 = 1 - self.b1**t
        bc2 = 1 - self.b2**t

        def upd(p, mm, vv, decay=1.0):
            p2 = p * (1.0 - lr * self.wd * decay)
            return p2 - (lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps)).astype(p.dtype)

        if self.decay_mask is not None:
            new_params = tmap(
                lambda p, mm, vv, msk: upd(p, mm, vv, jnp.asarray(msk, p.dtype)),
                params, m, v, self.decay_mask,
            )
        else:
            new_params = tmap(upd, params, m, v)
        return new_params, {"m": m, "v": v}


class LambCore(AdamCore):
    """Layer-wise adaptive rates (reference: operators/optimizers/lamb_op.cc,
    incubate DistributedFusedLamb)."""

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-6, lamb_weight_decay=0.01):
        super().__init__(beta1, beta2, epsilon)
        self.wd = lamb_weight_decay

    def update(self, grads, state, params, lr, step):
        m, v = self._moments(grads, state)
        t = step + 1
        bc1 = 1 - self.b1**t
        bc2 = 1 - self.b2**t

        def upd(p, mm, vv):
            r = (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps) + self.wd * p
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            r_norm = jnp.linalg.norm(r.astype(jnp.float32))
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return p - (lr * trust * r).astype(p.dtype)

        new_params = tmap(upd, params, m, v)
        return new_params, {"m": m, "v": v}


class AdagradCore:
    def __init__(self, epsilon=1e-6, initial_accumulator_value=0.0):
        self.eps = epsilon
        self.init_acc = initial_accumulator_value

    def init(self, params):
        return {"moment": tmap(lambda p: jnp.full_like(p, self.init_acc), params)}

    def update(self, grads, state, params, lr, step):
        mom = tmap(lambda a, g: a + jnp.square(g), state["moment"], grads)
        new_params = tmap(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + self.eps), params, grads, mom)
        return new_params, {"moment": mom}


class RMSPropCore:
    def __init__(self, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False):
        self.rho, self.eps, self.mu, self.centered = rho, epsilon, momentum, centered

    def init(self, params):
        s = {"mean_square": _zeros_like_tree(params), "moment": _zeros_like_tree(params)}
        if self.centered:
            s["mean_grad"] = _zeros_like_tree(params)
        return s

    def update(self, grads, state, params, lr, step):
        ms = tmap(lambda s, g: self.rho * s + (1 - self.rho) * jnp.square(g), state["mean_square"], grads)
        if self.centered:
            mg = tmap(lambda s, g: self.rho * s + (1 - self.rho) * g, state["mean_grad"], grads)
            denom = tmap(lambda s, g: jnp.sqrt(s - jnp.square(g) + self.eps), ms, mg)
        else:
            mg = None
            denom = tmap(lambda s: jnp.sqrt(s + self.eps), ms)
        mom = tmap(lambda v, g, d: self.mu * v + lr * g / d, state["moment"], grads, denom)
        new_params = tmap(lambda p, v: p - v, params, mom)
        new_state = {"mean_square": ms, "moment": mom}
        if self.centered:
            new_state["mean_grad"] = mg
        return new_params, new_state


class AdadeltaCore:
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.eps = rho, epsilon

    def init(self, params):
        return {"avg_sq_grad": _zeros_like_tree(params), "avg_sq_update": _zeros_like_tree(params)}

    def update(self, grads, state, params, lr, step):
        asg = tmap(lambda a, g: self.rho * a + (1 - self.rho) * jnp.square(g), state["avg_sq_grad"], grads)
        upd = tmap(lambda g, a, u: g * jnp.sqrt(u + self.eps) / jnp.sqrt(a + self.eps), grads, asg, state["avg_sq_update"])
        asu = tmap(lambda u, d: self.rho * u + (1 - self.rho) * jnp.square(d), state["avg_sq_update"], upd)
        new_params = tmap(lambda p, d: p - lr * d, params, upd)
        return new_params, {"avg_sq_grad": asg, "avg_sq_update": asu}


class AdamaxCore:
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def init(self, params):
        return {"m": _zeros_like_tree(params), "u": _zeros_like_tree(params)}

    def update(self, grads, state, params, lr, step):
        t = step + 1
        m = tmap(lambda m, g: self.b1 * m + (1 - self.b1) * g, state["m"], grads)
        u = tmap(lambda u, g: jnp.maximum(self.b2 * u, jnp.abs(g)), state["u"], grads)
        bc1 = 1 - self.b1**t
        new_params = tmap(lambda p, mm, uu: p - lr / bc1 * mm / (uu + self.eps), params, m, u)
        return new_params, {"m": m, "u": u}
