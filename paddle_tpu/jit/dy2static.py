"""Dygraph-to-static AST transpiler: Python control flow over traced values.

Reference: the @to_static AST transpiler
(python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:239 and
the 25 per-construct transformers in dygraph_to_static/ — ifelse_transformer,
loop_transformer, logical_transformer). This is the TPU-native minimal core:
instead of rewriting to fluid ConditionalBlock/While ops, rewritten control
flow dispatches at RUNTIME between plain Python execution (concrete
condition — exact Python semantics, zero overhead beyond a call) and the
XLA-native bridges ``static.nn.cond`` / ``static.nn.while_loop`` (traced
condition — compiles to lax.cond / lax.while_loop).

Supported rewrites:
- ``if``/``elif``/``else`` whose branches assign variables,
- ``while`` loops (loop-carried variables inferred from branch stores),
- ``for <name> in range(...)`` — runtime dispatch between a native Python
  loop (concrete bounds: trace-unrolled, exact semantics) and a
  while-loop form (traced bounds),
- ``break``/``continue`` in while/for-range loops (de-sugared into
  flag-guarded form, reference break_continue_transformer.py),
- ``return`` inside if branches (flag + continuation-into-else form,
  reference return_transformer.py) — all paths must return values of the
  same structure when the predicate is traced,
- ``and`` / ``or`` / ``not`` over tensors (Python short-circuit semantics
  are preserved for concrete values via lambdas).

Anything else (returns inside loops, tuple-target for loops, try/except,
break/continue inside try/with, in-place mutation in a branch —
subscript/attribute stores and mutating method calls like
``lst.append``/``d.update``/``t.add_``, …) is left untouched:
concrete-value code runs exactly as before, and a tensor-dependent
condition in unsupported shapes raises JAX's TracerBoolConversionError
pointing at the static.nn bridges.

Transformation is best-effort: if the source is unavailable (C extensions,
REPL, lambdas) the original function is used unchanged.
"""
from __future__ import annotations

import ast
import copy
import functools
import inspect
import textwrap


class _Undefined:
    """Sentinel for names not yet bound when a rewritten block runs
    (reference: dygraph_to_static UndefinedVar)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined local (dy2static)>"

    def _raise(self, *a, **k):
        raise NameError(
            "a local variable set in only one branch of rewritten control "
            "flow is referenced before assignment (dy2static); initialize "
            "it before the if/while statement")

    # any use of a variable left unbound by the taken branch fails loudly,
    # mirroring Python's UnboundLocalError-on-read (NameError subclass)
    __bool__ = __call__ = __iter__ = __len__ = __getattr__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __neg__ = __abs__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = __index__ = __float__ = __int__ = _raise


UNDEF = _Undefined()


def ld(f):
    """Best-effort read of an enclosing local: UNDEF when unbound."""
    try:
        return f()
    except NameError:  # includes UnboundLocalError (free-var unbound)
        return UNDEF


def _is_traced(v):
    from ..framework.core import Tensor
    from ..framework.static_trace import is_symbolic

    if not isinstance(v, Tensor):
        return False
    if is_symbolic(v._value):
        return True
    try:
        import jax.core

        return isinstance(v._value, jax.core.Tracer)
    except Exception:
        return False


def _concrete_bool(v):
    """bool(v) for anything concrete; None when v is traced."""
    if _is_traced(v):
        return None
    return bool(v)


def _check_defined(vals, names, what):
    for v, n in zip(vals, names):
        if v is UNDEF:
            raise NameError(
                f"variable '{n}' is used in a tensor-dependent {what} but is "
                f"not defined before it; XLA control flow needs every "
                f"carried/merged variable initialized up front")


def convert_ifelse(pred, true_fn, false_fn, names):
    b = _concrete_bool(pred)
    if b is not None:
        return true_fn() if b else false_fn()
    from ..static import nn as _snn
    from ..tensor._helpers import ensure_tensor

    def _wrap(fn):
        def run():
            out = fn()
            _check_defined(out, names, "if")
            return tuple(ensure_tensor(o) for o in out)

        return run

    out = _snn.cond(pred, _wrap(true_fn), _wrap(false_fn))
    return out if isinstance(out, tuple) else (out,)


def convert_while(cond_fn, body_fn, init, names):
    """Dispatch a rewritten ``while``: native Python while the condition is
    concrete, lax.while_loop once it is traced.

    When a de-sugared break/continue flag turns traced MID-loop (e.g.
    ``if i >= 2 and (x > 0): break`` — concrete short-circuit for i < 2,
    traced after), the traced loop resumes from the already-advanced loop
    vars: iterations completed concretely are kept, not re-executed.

    Limitation: Python-level side effects in the body (print / logging /
    list mutation / host RNG) still run once per *concrete* iteration plus
    exactly once more when JAX traces the remaining loop — lax.while_loop
    executes the Python body a single time at trace time regardless of trip
    count, so per-iteration side effects cannot be replayed on device.
    Side-effect-free bodies are unaffected.
    """
    b = _concrete_bool(cond_fn(*init))
    if b is not None:
        vals = tuple(init)
        while b:
            vals = tuple(body_fn(*vals))
            b = _concrete_bool(cond_fn(*vals))
            if b is None:
                if any(n.startswith(("_jst_brk", "_jst_cont")) for n in names):
                    # a de-sugared break/continue flag became traced: the
                    # flag-form body is pure over its loop vars (escape-
                    # scanned), so hand the ALREADY-ADVANCED vals to the
                    # traced loop — the concrete prefix is kept, only the
                    # remaining iterations compile
                    return _traced_while(cond_fn, body_fn, vals, names)
                raise TypeError(
                    "while condition became a traced tensor mid-loop; a "
                    "tensor-dependent while must start from tensor loop vars "
                    "(static.nn.while_loop)")
        return vals
    return _traced_while(cond_fn, body_fn, init, names)


def _traced_while(cond_fn, body_fn, init, names):
    from ..static import nn as _snn
    from ..tensor._helpers import ensure_tensor

    _check_defined(init, names, "while loop")
    out = _snn.while_loop(lambda *vs: cond_fn(*vs), lambda *vs: tuple(body_fn(*vs)),
                          [ensure_tensor(v) for v in init])
    return tuple(out)


def and_(f1, f2):
    v = f1()
    b = _concrete_bool(v)
    if b is not None:
        return f2() if b else v  # exact Python `and` semantics
    from ..tensor import logical_and
    from ..tensor._helpers import ensure_tensor

    return logical_and(ensure_tensor(v).astype("bool"), ensure_tensor(f2()).astype("bool"))


def or_(f1, f2):
    v = f1()
    b = _concrete_bool(v)
    if b is not None:
        return v if b else f2()
    from ..tensor import logical_or
    from ..tensor._helpers import ensure_tensor

    return logical_or(ensure_tensor(v).astype("bool"), ensure_tensor(f2()).astype("bool"))


def not_(v):
    b = _concrete_bool(v)
    if b is not None:
        return not b
    from ..tensor import logical_not
    from ..tensor._helpers import ensure_tensor

    return logical_not(ensure_tensor(v).astype("bool"))


def maybe_range(*args):
    """('py', range(...)) when all bounds are concrete ints, else
    ('t', (start, stop, step)) with traced bounds."""
    if not any(_is_traced(a) for a in args):
        return ("py", range(*(int(a) for a in args)))
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    else:
        start, stop, step = args
    if not _is_traced(step) and int(step) == 0:
        raise ValueError("range() arg 3 must not be zero")
    return ("t", (start, stop, step))


def concrete_true(flag):
    """bool(flag) when concrete, False when traced — lets an unrolled loop
    exit natively the moment a de-sugared break flag is concretely True,
    while traced flags keep unrolling (the guards mask the dead
    iterations)."""
    b = _concrete_bool(flag)
    return bool(b) if b is not None else False


def step_unless(brk, i, step):
    """The for-range while-form's synthesized step, gated on the de-sugared
    break flag: ``i`` unchanged once the break fired (Python leaves the
    loop target at the break iteration), ``i + step`` otherwise. The gate
    is on the break flag only — ``continue`` must still advance. Traced
    flags select via ``where`` so the loop stays compilable."""
    b = _concrete_bool(brk)
    if b is not None:
        return i if b else i + step
    from ..tensor._helpers import ensure_tensor

    i = ensure_tensor(i)
    # arithmetic select (not `where`): every operand routes through the
    # op layer, so it traces in both lax and static.nn subblocks
    keep = 1 - ensure_tensor(brk).astype(i.dtype)
    return i + ensure_tensor(step).astype(i.dtype) * keep


def is_py(r):
    return r[0] == "py"


def py_range(r):
    return r[1]


def range_start(r):
    return r[1][0]


def range_step(r):
    return r[1][2]


def range_cond(i, r):
    _, (start, stop, step) = r
    if isinstance(step, (int, float)):
        return (i < stop) if step > 0 else (i > stop)
    from ..tensor._helpers import ensure_tensor

    step = ensure_tensor(step)
    return (step > 0).logical_and(ensure_tensor(i) < stop).logical_or(
        (step <= 0).logical_and(ensure_tensor(i) > stop))


# ---------------------------------------------------------------------------
# AST rewriting
# ---------------------------------------------------------------------------

_JST = "__paddle_jst__"  # module alias injected into the caller's globals


def _stores(nodes):
    """Names (re)bound anywhere in ``nodes`` — Name(Store) covers assign,
    augassign, annassign, for targets, with-as, walrus."""
    out = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                out.add(sub.name)
    # generated helpers from inner rewrites are block-local, never carried
    return {n for n in out if not n.startswith("__jst_")}


class _EscapeScan(ast.NodeVisitor):
    """Detects constructs a rewritten block can't contain: return/yield
    anywhere, break/continue belonging to THIS level (not a nested loop),
    and scope/effect statements we refuse to relocate."""

    def __init__(self):
        self.found = False

    def generic_visit(self, node):
        if self.found:
            return
        super().generic_visit(node)

    def visit_Return(self, node):
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    def visit_YieldFrom(self, node):
        self.found = True

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_Global(self, node):
        self.found = True

    def visit_Nonlocal(self, node):
        self.found = True

    def visit_Import(self, node):
        self.found = True

    def visit_ImportFrom(self, node):
        self.found = True

    def visit_Delete(self, node):
        self.found = True

    # subscript/attribute stores are in-place mutation: correct when executed
    # natively, silently wrong when traced into a lax sub-block — refuse.
    def visit_Subscript(self, node):
        if isinstance(node.ctx, ast.Store):
            self.found = True
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Store):
            self.found = True
        self.generic_visit(node)

    # known-mutating method calls are in-place side effects like subscript
    # stores: under a traced predicate BOTH rewritten branch bodies run at
    # trace time, so the mutation would apply for the untaken branch too —
    # refuse the rewrite (native execution keeps Python semantics; a traced
    # predicate then raises TracerBoolConversionError instead of going
    # silently wrong). Matched conservatively to avoid refusing pure calls
    # that share a name (x.add(y), paddle.update_hub): plain names like
    # append/update only count as bare expression statements (result
    # discarded — pure calls there would be dead code), while paddle-style
    # trailing-underscore inplace methods (t.add_) count anywhere. A
    # value-used mutator (y = lst.pop()) still slips through — Python can't
    # distinguish that statically.
    _MUTATING = frozenset({
        "append", "extend", "insert", "remove", "pop", "clear", "sort",
        "reverse", "update", "setdefault", "popitem", "add", "discard"})

    @classmethod
    def _is_inplace_call(cls, node):
        f = node.func
        return (isinstance(f, ast.Attribute) and f.attr.endswith("_")
                and not f.attr.startswith("_")
                # the rewriter's own pure helpers (__paddle_jst__.and_/or_/
                # not_) share the trailing-underscore spelling — without
                # this exclusion any loop body containing a rewritten
                # bool-op could never convert to convert_while
                and not (isinstance(f.value, ast.Name) and f.value.id == _JST))

    @classmethod
    def _is_mutating_stmt(cls, node):
        return (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr in cls._MUTATING)

    def visit_Call(self, node):
        if self._is_inplace_call(node):
            self.found = True
        self.generic_visit(node)

    def visit_Expr(self, node):
        if self._is_mutating_stmt(node):
            self.found = True
        self.generic_visit(node)

    # break/continue inside a nested loop belong to that loop; returns/yields
    # still escape, so keep walking loop bodies but clear break/continue
    # significance by handling loops with a child scanner.
    def visit_For(self, node):
        self._nested_loop(node)

    def visit_While(self, node):
        self._nested_loop(node)

    def _nested_loop(self, node):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom,
                                ast.Global, ast.Nonlocal, ast.Import,
                                ast.ImportFrom, ast.Delete)):
                self.found = True
                return
            if (isinstance(sub, (ast.Subscript, ast.Attribute))
                    and isinstance(sub.ctx, ast.Store)):
                self.found = True
                return
            if isinstance(sub, ast.Call) and self._is_inplace_call(sub):
                self.found = True
                return
            if self._is_mutating_stmt(sub):
                self.found = True
                return

    # nested function/class bodies are separate scopes: return/yield inside
    # them is fine; don't descend.
    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_ClassDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _escapes(nodes):
    s = _EscapeScan()
    for n in nodes:
        s.visit(n)
        if s.found:
            return True
    return False


# public envelope tables: the pre-flight linter (paddle_tpu.analysis.ast_lint)
# flags exactly what this transpiler refuses to rewrite — same definitions,
# single source of truth
MUTATING_METHODS = _EscapeScan._MUTATING
is_inplace_call = _EscapeScan._is_inplace_call
is_mutating_stmt = _EscapeScan._is_mutating_stmt


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(attr):
    return ast.Attribute(value=_name(_JST), attr=attr, ctx=ast.Load())


def _jst_call(attr, args):
    return ast.Call(func=_jst_attr(attr), args=args, keywords=[])


def _ld_prologue(names):
    """``n = _jst.ld(lambda: n)`` for each name — normalizes unbound locals
    to UNDEF so they can be passed into rewritten blocks."""
    stmts = []
    for n in names:
        lam = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                               kw_defaults=[], kwarg=None, defaults=[]),
            body=_name(n))
        stmts.append(ast.Assign(targets=[_name(n, ast.Store())],
                                value=_jst_call("ld", [lam])))
    return stmts


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names], ctx=ctx or ast.Load())


# -- break/continue de-sugaring ---------------------------------------------
#
# Reference: dygraph_to_static/break_continue_transformer.py. A loop whose
# top-level body contains break/continue is rewritten into a pure
# flag-guarded form FIRST; the ordinary if/while machinery then compiles the
# flags (concrete flags run native Python, traced flags become lax control
# flow):
#
#   _brk = False; _cont = False
#   while (not _brk) and cond:
#       _cont = False
#       ... break -> _brk = True ; continue -> _cont = True ...
#       if not (_brk or _cont): <rest of body>


def _loop_escape_here(stmts):
    """break/continue belonging to THIS loop level: walk statements without
    descending into nested loops or function/class scopes."""
    for s in stmts:
        if isinstance(s, (ast.Break, ast.Continue)):
            return True
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(s, ast.If):
            if _loop_escape_here(s.body) or _loop_escape_here(s.orelse):
                return True
            continue
        for sub in ast.walk(s):
            if isinstance(sub, (ast.Break, ast.Continue)):
                return True  # break inside try/with: unsupported shape
    return False


def _flag_assign(name, value):
    return ast.Assign(targets=[_name(name, ast.Store())], value=ast.Constant(value=value))


def _guard_block(stmts, brk, cont):
    """Rewrite one statement block: break/continue become flag sets, the
    statements after a flag-setting `if` are wrapped in a not-flag guard.
    Returns None when the block has an unsupported shape (break inside
    try/with)."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(_flag_assign(brk, True))
            return out  # statements after an unconditional break are dead
        if isinstance(s, ast.Continue):
            out.append(_flag_assign(cont, True))
            return out
        if isinstance(s, ast.If) and (_loop_escape_here(s.body) or _loop_escape_here(s.orelse)):
            b = _guard_block(s.body, brk, cont)
            o = _guard_block(s.orelse, brk, cont)
            if b is None or o is None:
                return None
            out.append(ast.If(test=s.test, body=b or [ast.Pass()], orelse=o))
            rest = stmts[idx + 1:]
            if rest:
                sub = _guard_block(rest, brk, cont)
                if sub is None:
                    return None
                guard = ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
                    op=ast.Or(), values=[_name(brk), _name(cont)]))
                out.append(ast.If(test=guard, body=sub, orelse=[]))
            return out
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef)):
            out.append(s)  # nested loop/scope: its break/continue is its own
            continue
        for sub in ast.walk(s):
            if isinstance(sub, (ast.Break, ast.Continue)):
                return None  # e.g. inside try/with — refuse
        out.append(s)
    return out


# -- return-in-branch de-sugaring -------------------------------------------
#
# Reference: dygraph_to_static/return_transformer.py. `return` inside an if
# branch becomes `_jst_done = True; _jst_rv = value`; when the branch always
# returns, the statements after the `if` become its else (continuation into
# else — no undefined-value merge), otherwise they are wrapped in an
# `if not _jst_done:` guard. The function ends with `return _jst_rv`.

_RET_DONE, _RET_RV = "_jst_done", "_jst_rv"


def _always_returns(stmts):
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, ast.If) and s.orelse and _always_returns(s.body) and _always_returns(s.orelse):
            return True
    return False


def _branch_returns(stmts):
    """(has_return_inside_an_if, unsupported)."""
    has = False
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor, ast.Try, ast.With,
                          ast.AsyncWith)):
            for sub in ast.walk(s):
                if isinstance(sub, ast.Return):
                    return False, True  # return inside loop/try: unsupported
            continue
        if isinstance(s, ast.If):
            h1, u1 = _branch_returns(s.body)
            h2, u2 = _branch_returns(s.orelse)
            if u1 or u2:
                return False, True
            has = has or h1 or h2 or any(isinstance(b, ast.Return) for b in s.body + s.orelse)
    return has, False


def _rewrite_returns(stmts):
    """Rewrite a block: returns become flag+value sets. Returns (block,
    changed)."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.Return):
            out.append(_flag_assign(_RET_DONE, True))
            out.append(ast.Assign(targets=[_name(_RET_RV, ast.Store())],
                                  value=s.value or ast.Constant(value=None)))
            return out, True
        if isinstance(s, ast.If):
            b, c1 = _rewrite_returns(s.body)
            o, c2 = _rewrite_returns(s.orelse)
            if c1 or c2:
                rest = stmts[idx + 1:]
                if rest and _always_returns(s.body) and not s.orelse:
                    # continuation-into-else: every value path assigns _jst_rv
                    o, _ = _rewrite_returns(rest)
                    out.append(ast.If(test=s.test, body=b, orelse=o))
                    return out, True
                out.append(ast.If(test=s.test, body=b, orelse=o))
                if rest:
                    sub, _ = _rewrite_returns(rest)
                    out.append(ast.If(test=ast.UnaryOp(op=ast.Not(), operand=_name(_RET_DONE)),
                                      body=sub, orelse=[]))
                return out, True
            out.append(s)
            continue
        out.append(s)
    return out, False


def _desugar_returns(fdef):
    """Apply the return transform to a function body when it has returns
    inside if branches (and none inside loops/try). Returns True if
    rewritten."""
    has, unsupported = _branch_returns(fdef.body)
    if not has or unsupported:
        return False
    body, _ = _rewrite_returns(fdef.body)
    fdef.body = ([_flag_assign(_RET_DONE, False),
                  ast.Assign(targets=[_name(_RET_RV, ast.Store())],
                             value=ast.Constant(value=None))]
                 + body + [ast.Return(value=_name(_RET_RV))])
    for s in fdef.body:
        ast.copy_location(s, fdef)
        ast.fix_missing_locations(s)
    return True


class _Transformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0
        self.changed = False

    def _uid(self):
        self.n += 1
        return self.n

    # -- boolean operators ---------------------------------------------------

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        # a walrus inside an operand would rebind in the lambda's scope only
        if any(isinstance(s, ast.NamedExpr) for v in node.values for s in ast.walk(v)):
            return node
        self.changed = True
        fn = "and_" if isinstance(node.op, ast.And) else "or_"
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            thunk = lambda body: ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                                   kw_defaults=[], kwarg=None, defaults=[]),
                body=body)
            out = _jst_call(fn, [thunk(v), thunk(out)])
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.changed = True
            return ast.copy_location(_jst_call("not_", [node.operand]), node)
        return node

    # -- if / elif / else ----------------------------------------------------

    def visit_If(self, node):
        self.generic_visit(node)
        if getattr(node, "_jst_skip", False):
            return node
        outs = sorted(_stores(node.body) | _stores(node.orelse))
        if not outs or _escapes(node.body) or _escapes(node.orelse):
            return node
        uid = self._uid()
        tname, fname = f"__jst_true_{uid}", f"__jst_false_{uid}"

        def branch(fname_, body):
            args = ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in outs],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[_name(n) for n in outs])
            ret = ast.Return(value=_tuple_of(outs))
            return ast.FunctionDef(name=fname_, args=args,
                                   body=list(body) + [ret], decorator_list=[])

        call = _jst_call("convert_ifelse", [
            node.test, _name(tname), _name(fname),
            ast.Tuple(elts=[ast.Constant(value=n) for n in outs], ctx=ast.Load())])
        assign = ast.Assign(targets=[_tuple_of(outs, ast.Store())], value=call)
        stmts = (_ld_prologue(outs)
                 + [branch(tname, node.body), branch(fname, node.orelse or [ast.Pass()]), assign])
        for s in stmts:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        self.changed = True
        return stmts

    # -- while ---------------------------------------------------------------

    def visit_While(self, node):
        des = self._desugar_loop(node)
        if des is not None:
            self.changed = True
            out = []
            for s in des:  # fresh statements: run the full rewrite over them
                r = self.visit(s)
                out.extend(r if isinstance(r, list) else [r])
            return out
        self.generic_visit(node)
        return self._rewrite_while(node)

    def _desugar_loop(self, node):
        """While with top-level break/continue -> flag-guarded pure form
        (then rewritten by the ordinary machinery). None when inapplicable."""
        if getattr(node, "_jst_skip", False) or node.orelse:
            return None
        if not _loop_escape_here(node.body):
            return None
        uid = self._uid()
        brk, cont = f"_jst_brk{uid}", f"_jst_cont{uid}"
        guarded = _guard_block(node.body, brk, cont)
        if guarded is None:
            return None
        test = ast.BoolOp(op=ast.And(), values=[
            ast.UnaryOp(op=ast.Not(), operand=_name(brk)), node.test])
        wl = ast.While(test=test,
                       body=[_flag_assign(cont, False)] + guarded, orelse=[])
        stmts = [_flag_assign(brk, False), _flag_assign(cont, False), wl]
        for s in stmts:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return stmts

    def _rewrite_while(self, node):
        if getattr(node, "_jst_skip", False) or node.orelse:
            return node
        loop_vars = sorted(_stores(node.body))
        if not loop_vars or _escapes(node.body):
            return node
        uid = self._uid()
        cname, bname = f"__jst_cond_{uid}", f"__jst_body_{uid}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in loop_vars],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
        cond_fn = ast.FunctionDef(
            name=cname, args=copy.deepcopy(args),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=bname, args=copy.deepcopy(args),
            body=list(node.body) + [ast.Return(value=_tuple_of(loop_vars))],
            decorator_list=[])
        call = _jst_call("convert_while", [
            _name(cname), _name(bname), _tuple_of(loop_vars),
            ast.Tuple(elts=[ast.Constant(value=n) for n in loop_vars], ctx=ast.Load())])
        assign = ast.Assign(targets=[_tuple_of(loop_vars, ast.Store())], value=call)
        stmts = _ld_prologue(loop_vars) + [cond_fn, body_fn, assign]
        for s in stmts:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        self.changed = True
        return stmts

    # -- for <name> in range(...) -------------------------------------------

    def visit_For(self, node):
        self.generic_visit(node)
        has_bc = (not node.orelse) and _loop_escape_here(node.body)
        if (node.orelse or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3
                or any(isinstance(a, ast.Starred) for a in node.iter.args)
                or (_escapes(node.body) and not has_bc)
                # a body that rebinds the target diverges from for semantics
                # in the while-form (the rebound value would be carried)
                or node.target.id in _stores(node.body)):
            return node
        uid = self._uid()
        rname = f"__jst_range_{uid}"
        tgt = node.target.id
        r_assign = ast.Assign(targets=[_name(rname, ast.Store())],
                              value=_jst_call("maybe_range", list(node.iter.args)))
        pre = []
        if has_bc:
            # de-sugar break/continue to flags. Concrete-bounds path: a
            # statically-unrolled loop whose per-iteration body is masked by
            # the flags — with concrete flags convert_ifelse dispatches
            # natively (exact Python break/continue semantics); with a
            # TRACED break predicate the guards become lax.cond, which keeps
            # the loop differentiable (reverse-mode through lax.while_loop
            # is impossible, so the canonical loop-with-break example must
            # unroll). Traced-bounds path: flag-carried while, forward-only.
            brk, cont = f"_jst_brk{uid}", f"_jst_cont{uid}"
            guarded = _guard_block(copy.deepcopy(node.body), brk, cont)
            if guarded is None or _escapes(guarded):
                return node
            pre = [_flag_assign(brk, False), _flag_assign(cont, False)]
            # rewrite the guard content NOW; the assembled loop is not
            # re-visited (its native early-exit break must stay native)
            guard_if = ast.If(test=ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                              body=copy.deepcopy(guarded), orelse=[])
            ast.copy_location(guard_if, node)
            ast.fix_missing_locations(guard_if)
            visited_guard = self.visit(guard_if)
            visited_guard = visited_guard if isinstance(visited_guard, list) else [visited_guard]
            # native early exit once the break flag is CONCRETELY true —
            # checked at the END of the iteration body, BEFORE the for
            # statement rebinds the target, so the post-loop target equals
            # Python's (the break iteration, not one past it); a traced
            # flag keeps unrolling behind the guards
            early = ast.If(test=_jst_call("concrete_true", [_name(brk)]),
                           body=[ast.Break()], orelse=[])
            early._jst_skip = True
            py_body = [_flag_assign(cont, False)] + visited_guard + [early]
        else:
            py_body = copy.deepcopy(node.body)
        # python path: loop over the concrete range
        py_loop = ast.For(target=ast.Name(id=tgt, ctx=ast.Store()),
                          iter=_jst_call("py_range", [_name(rname)]),
                          body=py_body, orelse=[])
        py_loop._jst_skip = True
        # traced-bounds path: while-form, rewritten through the while
        # machinery
        init = ast.Assign(targets=[_name(tgt, ast.Store())],
                          value=_jst_call("range_start", [_name(rname)]))
        step = ast.Assign(
            targets=[_name(tgt, ast.Store())],
            value=ast.BinOp(left=_name(tgt), op=ast.Add(),
                            right=_jst_call("range_step", [_name(rname)])))
        test = _jst_call("range_cond", [_name(tgt), _name(rname)])
        if has_bc:
            # the synthesized step is gated on the break flag (step_unless)
            # so the target is not advanced past the break; `continue`
            # still advances — the gate ignores the continue flag
            step = ast.Assign(
                targets=[_name(tgt, ast.Store())],
                value=_jst_call("step_unless", [
                    _name(brk), _name(tgt),
                    _jst_call("range_step", [_name(rname)])]))
            wl_body = [_flag_assign(cont, False)] + copy.deepcopy(guarded)
            test = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(), operand=_name(brk)), test])
        else:
            wl_body = copy.deepcopy(node.body)
        wl = ast.While(test=test, body=wl_body + [step], orelse=[])
        for s in pre + [py_loop, wl]:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        if has_bc:
            py_loop = self.visit(py_loop)  # fresh guard ifs need a full pass
            rewritten = self.visit(wl)
        else:
            rewritten = self._rewrite_while(wl)
        py_stmts = py_loop if isinstance(py_loop, list) else [py_loop]
        traced_stmts = [init] + (rewritten if isinstance(rewritten, list) else [rewritten])
        dispatch = ast.If(test=_jst_call("is_py", [_name(rname)]),
                          body=py_stmts, orelse=traced_stmts)
        dispatch._jst_skip = True
        stmts = [r_assign] + pre + [dispatch]
        for s in stmts:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return stmts


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

# compiled factory per code object: the expensive parse/transform/compile is
# shared across sibling closures; each closure gets its own factory call so
# captured cell values stay per-instance (incl. the __class__ cell zero-arg
# super() needs).
_FACTORY = "__jst_factory__"
_code_cache = {}


def _build_factory(fn):
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []
    # private-name mangling (self.__x -> self._Cls__x) happens at class-body
    # compile time; recompiling outside the class would silently unmangle
    for sub in ast.walk(tree):
        nm = sub.attr if isinstance(sub, ast.Attribute) else (
            sub.id if isinstance(sub, ast.Name) else None)
        if nm and nm.startswith("__") and not nm.endswith("__"):
            return None
    ret_changed = _desugar_returns(fdef)
    t = _Transformer()
    t.visit(tree)
    if not (t.changed or ret_changed):  # nothing rewritten — keep original
        return None
    freevars = fn.__code__.co_freevars
    factory = ast.FunctionDef(
        name=_FACTORY,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=n) for n in freevars],
                           vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=[fdef, ast.Return(value=_name(fdef.name))],
        decorator_list=[])
    mod = ast.Module(body=[factory], type_ignores=[])
    ast.copy_location(factory, fdef)
    ast.fix_missing_locations(mod)
    return compile(mod, filename=fn.__code__.co_filename or "<dy2static>", mode="exec")


def transpile(fn):
    """Rewrite ``fn``'s control flow; returns ``fn`` unchanged when the
    source is unavailable, nothing is rewritable, the rewrite fails, or
    ProgramTranslator.enable(False) turned rewriting off."""
    from . import ProgramTranslator

    if not getattr(ProgramTranslator, "enabled", True):
        return fn
    if getattr(fn, "_jst_not_to_static", False) or getattr(fn, "_jst_transpiled", False):
        return fn
    key = getattr(fn, "__code__", None)
    if key is None:
        return fn
    if key in _code_cache:
        code = _code_cache[key]
    else:
        try:
            code = _build_factory(fn)
        except (OSError, TypeError, SyntaxError, KeyError, IndentationError):
            code = None
        _code_cache[key] = code
    if code is None:
        return fn
    try:
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
    except ValueError:  # an empty cell (e.g. not-yet-bound recursive ref)
        return fn
    import sys

    # the rewritten function's globals ARE fn's module globals (live lookups,
    # recursion resolves the decorated name); only the runtime-helper alias
    # is injected, under a collision-safe name.
    g = fn.__globals__
    g.setdefault(_JST, sys.modules[__name__])
    try:
        lns = {}
        exec(code, g, lns)
        new = lns[_FACTORY](*cells)
    except Exception:
        # e.g. a default-arg expression referencing an enclosing local that
        # is not one of fn's freevars — fall back to the original function
        _code_cache[key] = None
        return fn
    new = functools.wraps(fn)(new)
    new._jst_transpiled = True
    return new


def not_to_static(fn):
    """Mark ``fn`` so @to_static skips AST rewriting (reference:
    paddle.jit.not_to_static)."""
    fn._jst_not_to_static = True
    return fn
