"""paddle_tpu.jit — the static-graph execution path.

Parity: the reference's whole static stack — ProgramDesc + InterpreterCore
(paddle/fluid/framework/new_executor/interpretercore.cc:116),
``@paddle.jit.to_static`` (dygraph_to_static/program_translator.py:239) and
``paddle.jit.save`` — collapses to jax.jit tracing of the functional layer
call. The "program" is the jaxpr; the "executor" is XLA; data-transfer
insertion, stream analysis, GC and op scheduling are XLA's problem.
"""
from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import sanitizer as _sanitizer
from ..framework import random as _random
from ..framework.autograd import no_grad
from ..framework.core import Tensor, _wrap_value, unwrap
from ..nn.functional_api import _wrap_tree, unwrap_tree


def _pure_model_call(model, arrays, args, kwargs, training, rng):
    """Run model under bound arrays; return (output, updated_buffer_arrays).

    Buffer side effects (BatchNorm running stats) are captured as explicit
    outputs — the jit-path equivalent of the reference's in-place buffer
    mutation (paddle/phi/kernels/gpu/batch_norm_kernel.cu writes mean/var out).
    """
    modes = [(l, l.training) for l in model.sublayers(include_self=True)]
    for l, _ in modes:
        l.training = training
    rng_ctx = _random.rng_scope(rng) if rng is not None else contextlib.nullcontext()
    buf_names = [n for n, _ in model.named_buffers()]
    try:
        with no_grad(), model.bind(arrays), rng_ctx:
            out = model(*_wrap_tree(list(args)), **kwargs)
            new_buffers = {}
            for n, b in model.named_buffers():
                new_buffers[n] = b._value
    finally:
        for l, was in modes:
            l.training = was
    return unwrap_tree(out), new_buffers


def scan_steps(step, *, length=None, with_consts=False, donate_argnums=0, **jit_kwargs):
    """ONE-dispatch multi-step runner: jit(lax.scan(step)) with donated carry.

    The PR-3 ``TrainStep.run_steps`` idiom as a shared helper: ``step`` is a
    scan body ``(carry, x) -> (carry, y)`` and the returned jitted function
    ``run(carry, xs=None)`` chains every iteration inside ONE compiled
    program — one host dispatch (and one host sync, when the caller reads)
    per K steps instead of per step. With ``with_consts=True`` the body is
    ``(consts, carry, x) -> (carry, y)`` and ``run(consts, carry, xs=None)``
    threads ``consts`` (e.g. model params) through untouched — keep them out
    of the carry so donation never consumes them. ``length`` pins the trip
    count when ``xs`` is None (the serving engine's fused decode);
    ``jit_kwargs`` pass through to ``jax.jit`` (shardings etc.).
    """
    if with_consts:
        def run(consts, carry, xs=None):
            return jax.lax.scan(functools.partial(step, consts), carry, xs, length=length)
    else:
        def run(carry, xs=None):
            return jax.lax.scan(step, carry, xs, length=length)
    return jax.jit(run, donate_argnums=donate_argnums, **jit_kwargs)


class TrainStep:
    """One compiled training step: forward + backward + optimizer update.

    ``loss_fn(output, *labels)`` runs on Tensors (any paddle_tpu loss).
    Donates the state buffers so param memory stays flat (reference analog:
    inplace/vars GC in interpretercore; here it's XLA buffer donation).

    ``guard=True`` (or ``FLAGS_train_guard``) fuses the training-health
    guard into the program: an all-finite reduction over loss+grads whose
    bad-step flag masks the param/opt/buffer/step update with ``jnp.where``
    — state stays bitwise at its pre-step value on a NaN/Inf gradient, with
    no extra dispatch and no host sync. Metrics gain a device-resident
    ``health`` leaf ``{bad_step, grad_norm, skipped}`` (stacked ``[K]``
    under ``run_steps``) for :class:`paddle_tpu.stability.HealthMonitor`.
    A skipped step does NOT advance ``state["step"]`` (rng fold-in and LR
    schedule stay aligned with a run that never saw the bad batch); the
    cumulative skip count lives in ``state["skipped"]``.
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None, state_shardings=None, batch_shardings=None, remat=False, seed=0, amp_level=None, amp_dtype="bfloat16", accumulate_steps=1, return_outputs=False, guard=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.accumulate_steps = int(accumulate_steps)
        self.return_outputs = return_outputs  # include model outputs in metrics (hapi train-metric path)
        from ..framework.flags import flag as _flag

        # Training-health guard (stability subsystem): fuse an all-finite
        # reduction over loss+grads into the step program and skip the
        # param/opt/step update in-graph when it trips — state stays bitwise
        # at its pre-step value (correct under donation: the select happens
        # inside the compiled program). Metrics gain a device-resident
        # "health" leaf; no extra dispatch, no per-step host sync.
        self.guard = bool(_flag("FLAGS_train_guard")) if guard is None else bool(guard)
        # Deterministic chaos: inject non-finite gradients at a named step
        # (read HERE, at construction — the injection compiles into the
        # program, gated by an armed budget carried in the state so it fires
        # exactly once per process even across scans and rollbacks).
        from ..testing import chaos as _chaos

        self._nan_chaos = _chaos.nan_grads_due()
        # AMP (reference amp.decorate semantics, bf16-first for TPU).
        # O2: master params stay f32 in state; compute casts params+inputs to
        #     amp_dtype so matmuls hit the MXU at bf16; loss input back to f32.
        # O1: white/black-list autocast via the amp module's primitive hook.
        if amp_level not in (None, "O0", "O1", "O2"):
            raise ValueError(f"amp_level must be None/'O0'/'O1'/'O2', got {amp_level!r}")
        self.amp_level = None if amp_level == "O0" else amp_level
        self.amp_dtype = jnp.dtype(amp_dtype) if self.amp_level else None
        if self.amp_dtype == jnp.float16:
            raise ValueError(
                "float16 in the fused TrainStep has no loss-scaling hook and "
                "gradients underflow silently; use bfloat16 (TPU-native) or "
                "the eager path with amp.GradScaler")
        from ..framework.flags import flag

        if not remat and flag("FLAGS_remat_policy") != "none":
            remat = True
        params = model.param_arrays()
        buffers = model.buffer_arrays()
        self.state = {
            "params": params,
            "buffers": buffers,
            "opt": optimizer.core.init(params),
            "step": jnp.zeros((), jnp.int32),
            "rng": jax.random.key(seed),
        }
        if self.guard:
            # dispatched-but-skipped update count; step + skipped together
            # form the monotonic dispatch counter (step alone freezes on a
            # skipped update so rng fold-in stays aligned with a clean run)
            self.state["skipped"] = jnp.zeros((), jnp.int32)
        if self._nan_chaos is not None:
            self.state["chaos_nan_armed"] = jnp.asarray(self._nan_chaos[1], jnp.int32)
        self._remat = remat
        self._batch_shardings = batch_shardings
        self._state_shardings = state_shardings
        if mesh is not None and isinstance(state_shardings, dict):
            from jax.sharding import NamedSharding, PartitionSpec as P

            extras = [e for e in ("skipped", "chaos_nan_armed")
                      if e in self.state and e not in state_shardings]
            if extras:  # guard/chaos scalar leaves ride along replicated
                state_shardings = dict(state_shardings)
                for extra in extras:
                    state_shardings[extra] = NamedSharding(mesh, P())
                self._state_shardings = state_shardings
        self._build(remat)
        if mesh is not None and state_shardings is not None:
            self.state = jax.device_put(self.state, state_shardings)
        self._make_jits()
        # observability: per-batch-signature AOT executables (the retained
        # XLA Compiled handles behind explain()), their cost rows, and the
        # host-side step counter the run log indexes by
        self._compiled: Dict[tuple, Any] = {}
        self._specializations: list = []
        self._host_step = 0

    def _make_jits(self):
        if self.mesh is not None and self._state_shardings is not None:
            self._jit = jax.jit(self._step, donate_argnums=0, in_shardings=(self._state_shardings, self._batch_shardings), out_shardings=(self._state_shardings, None))
            self._jit_multi = scan_steps(self._step, donate_argnums=0, in_shardings=(self._state_shardings, None), out_shardings=(self._state_shardings, None))
        else:
            self._jit = jax.jit(self._step, donate_argnums=0)
            self._jit_multi = scan_steps(self._step, donate_argnums=0)

    def rebuild(self):
        """Re-trace and re-jit the step programs against the CURRENT
        optimizer/model hyperparameters (the compiled programs bake closed-
        over host scalars — e.g. a plain-float learning rate — so a
        divergence rollback's LR backoff only takes effect through a
        rebuild). State is preserved; compiled-specialization caches are
        dropped (next dispatch recompiles)."""
        self._build(self._remat)
        self._make_jits()
        self._compiled = {}

    def _build(self, remat):
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss_fn
        amp_dt, amp_level = self.amp_dtype, self.amp_level
        o2 = amp_level == "O2"

        def _to_amp(tree):
            return jax.tree_util.tree_map(
                lambda a: a.astype(amp_dt) if a.dtype == jnp.float32 else a, tree)

        def _to_f32(x):
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32) if a.dtype == amp_dt else a, x)

        def loss_of(params, buffers, inputs, labels, rng):
            def call(p):
                if o2:
                    # cast-through: grads of the cast are a cast back, so the
                    # optimizer sees f32 grads against f32 master params
                    p = _to_amp(p)
                    inputs_c = _to_amp(inputs)
                else:
                    inputs_c = inputs
                if amp_level == "O1":  # white/black-list autocast (traced)
                    from .. import amp as _amp

                    ctx = _amp.auto_cast(True, level="O1", dtype=str(amp_dt))
                else:
                    ctx = contextlib.nullcontext()
                with ctx:
                    out, new_buffers = _pure_model_call(model, {**p, **buffers}, inputs_c, {}, True, rng)
                with no_grad():
                    loss_t = loss_fn(*_wrap_tree([out]), *_wrap_tree(list(labels)))
                loss_v = unwrap(loss_t)
                if amp_dt is not None and loss_v.dtype == amp_dt:
                    # loss scalar in f32 (amp black list); the loss fns do
                    # their reductions in f32 internally — logits stay bf16,
                    # which avoids materializing an f32 [..., vocab] tensor
                    loss_v = loss_v.astype(jnp.float32)
                return loss_v, (out, new_buffers)

            if remat:
                # rematerialize the forward in backward (paddle recompute /
                # fleet/utils/recompute.py:209 parity via jax.checkpoint)
                call = jax.checkpoint(call)
            return call(params)

        k = self.accumulate_steps
        guard = self.guard
        nan_chaos = self._nan_chaos

        def _step(state, batch):
            inputs, labels = batch
            rng = jax.random.fold_in(state["rng"], state["step"])
            if k <= 1:
                (loss, (out, new_buffers)), grads = jax.value_and_grad(loss_of, has_aux=True)(
                    state["params"], state["buffers"], inputs, labels, rng
                )
            else:
                # gradient merge (parity: fleet/meta_optimizers/
                # gradient_merge_optimizer.py): k microbatches through a
                # lax.scan, summed grads, one optimizer update. The strided
                # microbatch split (rows [i::k]) is shared with the pipeline.
                from ..distributed.pipeline import microbatch

                mb_in = jax.tree_util.tree_map(lambda a: microbatch(a, k), inputs)
                mb_lb = jax.tree_util.tree_map(lambda a: microbatch(a, k), labels)
                zeros = jax.tree_util.tree_map(jnp.zeros_like, state["params"])

                def acc(carry, xs):
                    gsum, lsum, buffers = carry
                    i, mi, ml = xs
                    (l, (o, nb)), g = jax.value_and_grad(loss_of, has_aux=True)(
                        state["params"], buffers, mi, ml, jax.random.fold_in(rng, i)
                    )
                    gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
                    # per-microbatch outputs stack up for hapi metrics (the
                    # scan ys); stacked as [k, mb, ...] and re-interleaved
                    # below so metric updates see the whole batch
                    ys = o if self.return_outputs else None
                    return (gsum, lsum + l, nb), ys

                (gsum, lsum, new_buffers), mb_out = jax.lax.scan(
                    acc, (zeros, jnp.zeros((), jnp.float32), state["buffers"]),
                    (jnp.arange(k), mb_in, mb_lb))
                grads = jax.tree_util.tree_map(lambda g: g / k, gsum)
                loss = lsum / k
                if self.return_outputs and mb_out is not None:
                    from ..distributed.pipeline import unmicrobatch as _unmb

                    out = jax.tree_util.tree_map(_unmb, mb_out)
            new_state = {"rng": state["rng"]}
            if nan_chaos is not None:
                # deterministic non-finite-gradient injection: fires while
                # the armed budget lasts, counted on the monotonic dispatch
                # counter (step+skipped), then drains — exactly once per
                # process under __call__, run_steps AND post-rollback replay
                at, _n = nan_chaos
                ctr = state["step"] + (state["skipped"] if guard else 0)
                fire = (state["chaos_nan_armed"] > 0) & (ctr >= at)
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(fire, jnp.full_like(g, jnp.nan), g), grads)
                new_state["chaos_nan_armed"] = (
                    state["chaos_nan_armed"] - fire.astype(jnp.int32))
            if guard:
                # ONE fused reduction per grad leaf: the f32 sum-of-squares
                # feeds both the global grad norm and the finite flag (any
                # NaN/Inf grad makes the accumulator non-finite; an
                # accumulator that overflows f32 marks the step bad too —
                # such a step is garbage regardless). Cheaper than a second
                # isfinite pass over every gradient.
                sumsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in jax.tree_util.tree_leaves(grads))
                gnorm = jnp.sqrt(sumsq)
                bad = ~(jnp.isfinite(sumsq) & jnp.isfinite(loss))
            new_params, new_opt, lr = optimizer._traced_update(
                grads, state["opt"], state["params"], state["step"])
            if guard:
                # bad-step skip: select the PRE-step value for every state
                # leaf inside the compiled program — bitwise no-op update,
                # correct under donate_argnums (nothing escaped the program)
                sel = lambda new, old: jnp.where(bad, old, new)  # noqa: E731
                new_params = jax.tree_util.tree_map(sel, new_params, state["params"])
                new_opt = jax.tree_util.tree_map(sel, new_opt, state["opt"])
                new_buffers = jax.tree_util.tree_map(sel, new_buffers, state["buffers"])
                new_step = jnp.where(bad, state["step"], state["step"] + 1)
                new_state["skipped"] = state["skipped"] + bad.astype(jnp.int32)
            else:
                new_step = state["step"] + 1
            new_state.update(params=new_params, buffers=new_buffers,
                             opt=new_opt, step=new_step)
            metrics = {"loss": loss, "lr": lr}
            if guard:
                metrics["health"] = {"bad_step": bad, "grad_norm": gnorm,
                                     "skipped": new_state["skipped"]}
            if self.return_outputs:
                metrics["outputs"] = out
            return new_state, metrics

        # K steps in one XLA dispatch: _step is the scan body for the shared
        # scan_steps() runner built in _make_jits — the compiled program
        # chains K forward+backward+update iterations on-device, the
        # InterpreterCore's per-op scheduling amortized to one host
        # round-trip per K steps
        self._step = _step

    @staticmethod
    def _as_arrays(x):
        return tuple(unwrap(v) if isinstance(v, Tensor) else jnp.asarray(v)
                     for v in (x if isinstance(x, (list, tuple)) else [x]))

    def _dispatch(self, which, jitfn, batch):
        """Run one compiled dispatch, compiling through the AOT path on a
        new (kind, batch-shape) signature so the XLA Compiled handle — the
        only source of cost_analysis/memory_analysis — is retained for the
        run log and :meth:`explain`. Falls back to the plain jitted call
        whenever AOT is unavailable; dispatch never breaks for telemetry."""
        if _sanitizer.enabled():
            # pre-flight: a donated-and-deleted state leaf raises a
            # structured StaleStateError naming the leaf path, instead of
            # XLA's opaque deleted-buffer crash mid-dispatch; numpy batch
            # leaves become explicit device uploads so the dispatch itself
            # runs transfer-clean under the guard below
            _sanitizer.check_state("train_step", self.state, label=which)
            batch = _sanitizer.explicit_device(batch)
        sig = (which,) + tuple((tuple(l.shape), str(l.dtype))
                               for l in jax.tree_util.tree_leaves(batch))
        entry = self._compiled.get(sig)
        if entry is None:
            _sanitizer.note_compile("train_step", which, sig[1:])
            from ..observability import introspect as _introspect
            from ..observability import runlog as _runlog
            from ..observability import span as _span
            from ..profiler import counter_inc

            label = which + "/" + ",".join(
                f"{d}{list(s)}" for s, d in sig[1:5])  # first few batch leaves
            with _span("train_step.compile"):
                # FLAGS_compile_cache_dir: the compiled step round-trips
                # through the on-disk AOT store keyed on the lowered program
                # text — a warm restart (or an elastic resume onto a mesh
                # the planner already evaluated during HOLD) loads the
                # executable instead of recompiling
                compiled, info = _introspect.aot_compile(
                    jitfn, (self.state, batch), cache_scope="train_step")
            entry = compiled if compiled is not None else jitfn
            if compiled is not None:
                from ..framework.flags import flag as _flag

                if _flag("FLAGS_shard_check"):
                    # SPMD pre-flight (PTA2xx) once per new specialization,
                    # BEFORE the executable is cached or dispatched: budget/
                    # divergence errors abort here, reshard findings warn
                    from ..analysis import spmd as _spmd

                    shardings = self._state_shardings
                    psh = shardings.get("params") if isinstance(shardings, dict) else None
                    report = _spmd.shard_check(
                        compiled, component="train_step", label=label,
                        kind=which, params=self.state.get("params"),
                        param_shardings=psh)
                    info["spmd"] = report.summary()
            self._compiled[sig] = entry
            if info.get("from_disk_cache"):
                counter_inc("train_step.aot_cache_hits")
            else:
                counter_inc("train_step.compiles")
                if info.get("aot_cache_stored"):
                    counter_inc("train_step.aot_cache_stores")
            info["label"] = label
            info["kind"] = which
            self._specializations.append(info)  # noqa: PTA305 (one entry per compiled signature — bounded by the recompile-churn sentinel under FLAGS_sanitize)
            _runlog.emit("compile", component="train_step", label=label,
                         seconds=info.get("compile_seconds"),
                         cached=bool(info.get("from_disk_cache")),
                         flops=info.get("flops"),
                         bytes_accessed=info.get("bytes_accessed"),
                         peak_bytes=info.get("peak_bytes"))
        try:
            try:
                with _sanitizer.transfer_scope(f"train_step.{which}"):
                    out = entry(self.state, batch)
            except (TypeError, ValueError):
                if entry is jitfn:
                    raise
                # AOT executables validate avals strictly; on drift fall back to
                # the jitted path permanently for this signature
                self._compiled[sig] = jitfn
                with _sanitizer.transfer_scope(f"train_step.{which}"):
                    out = jitfn(self.state, batch)
            if _sanitizer.enabled():
                import itertools

                # the dispatch donated the old state; eager model Tensors
                # still referencing those buffers get poisoned so any later
                # use raises StaleStateError instead of crashing in XLA
                _sanitizer.sweep_tensors(
                    "train_step",
                    itertools.chain(self.model.named_parameters(),
                                    self.model.named_buffers()),
                    label=which)
            return out
        except Exception as exc:
            # unhandled dispatch fault (aval drift already fell back above):
            # leave a flight-recorder dump, then let the fault propagate
            from ..observability import flightrec as _flightrec

            _flightrec.dump("dispatch_exception", exc,
                            component="train_step", which=which,
                            step=self._host_step)
            raise

    def __call__(self, inputs, labels):
        from ..observability import runlog as _runlog
        from ..observability import span as _span
        from ..profiler import counter_inc

        from ..observability import trace as _trace

        inputs = self._as_arrays(inputs)
        labels = self._as_arrays(labels)
        with _span("train_step.step") as sp:
            self.state, metrics = self._dispatch("step", self._jit, (inputs, labels))
        counter_inc("train_step.dispatches")
        counter_inc("train_step.steps")
        self._host_step += 1
        _runlog.emit("step", step=self._host_step, component="train_step",
                     k=1, seconds=sp.seconds, trace=_trace.current_trace())
        return {k: _wrap_tree(v) for k, v in metrics.items()}

    def run_steps(self, batches, k=None):
        """Run K training steps in ONE jitted dispatch (lax.scan over the
        step body, state donated).

        ``batches`` is either

        * a sequence of K per-step ``(inputs, labels)`` batches (``k`` may be
          omitted) — stacked here along a new leading axis, or
        * a pre-stacked ``(inputs, labels)`` pair whose leaves already carry
          the leading ``[k, ...]`` axis (what ``io.DataLoader(fuse_steps=k)``
          yields) — then ``k`` must be passed.

        Returns the metrics dict with every leaf stacked ``[k, ...]`` as
        device-resident arrays: nothing syncs the host until the caller
        reads a value (log boundaries), so the loop costs one Python
        dispatch per K steps instead of per step. Bitwise-identical to K
        individual ``__call__`` steps (same step fn, same per-step rng
        fold-in on the carried counter).
        """
        if k is None:
            batches = list(batches)
            k = len(batches)
            if k == 0:
                raise ValueError("run_steps needs at least one batch")
            norm = [(self._as_arrays(i), self._as_arrays(l)) for i, l in batches]
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *norm)
        else:
            k = int(k)
            inputs, labels = batches
            stacked = (self._as_arrays(inputs), self._as_arrays(labels))
            for leaf in jax.tree_util.tree_leaves(stacked):
                if leaf.shape[:1] != (k,):
                    raise ValueError(
                        f"pre-stacked batch leaf has leading dim {leaf.shape[:1]}, "
                        f"expected ({k},); pass per-step batches without k= to "
                        "have run_steps stack them")
        from ..observability import measured as _measured
        from ..observability import runlog as _runlog
        from ..observability import span as _span
        from ..observability import trace as _trace
        from ..profiler import counter_inc

        with _span("train_step.run_steps") as sp:
            self.state, metrics = self._dispatch("run_steps", self._jit_multi, stacked)
        counter_inc("train_step.dispatches")
        counter_inc("train_step.steps", k)
        self._host_step += k
        _runlog.emit("step", step=self._host_step, component="train_step",
                     k=k, seconds=sp.seconds, trace=_trace.current_trace())
        # measured step times, keyed by the auto-parallel plan fingerprint
        # (planner.build_step attaches .plan) — the evidence base the cost
        # model can calibrate against (persistence + schema this PR)
        fp = getattr(getattr(self, "plan", None), "fingerprint", None)
        if fp and sp.seconds is not None:
            _measured.record(fp, sp.seconds, k)
        from ..observability import slo as _slo

        # judgment layer: cadence-gated host-side evaluate — a single flag
        # check per dispatch until FLAGS_slo (or an explicit install) arms it
        _slo.on_tick()
        return {name: _wrap_tree(v) for name, v in metrics.items()}

    def explain(self, analyze: bool = False) -> list:
        """Per-specialization cost table: one row per compiled (kind,
        batch-shape) signature with the XLA ``cost_analysis``/
        ``memory_analysis`` captured at compile time (flops, bytes accessed,
        peak device memory, compile seconds). Render with
        ``paddle_tpu.observability.format_cost_table``; bench.py prints it.

        ``analyze=True`` additionally runs the SPMD sharding analyzer
        (paddle_tpu.analysis.spmd, PTA2xx) over each retained executable and
        attaches its verdict under the row's ``"spmd"`` key (collective
        counts, estimated reshard bytes, schedule fingerprint, findings) —
        works whether or not ``FLAGS_shard_check`` was on at compile time.
        """
        rows = [dict(r) for r in self._specializations]
        if analyze:
            from ..analysis import spmd as _spmd

            shardings = self._state_shardings
            psh = shardings.get("params") if isinstance(shardings, dict) else None
            # _compiled inserts exactly one entry per _specializations row,
            # in the same order (an aval-drift fallback swaps the value for
            # the plain jitfn, which has no retained HLO — skipped)
            for row, entry in zip(rows, list(self._compiled.values())):
                if "spmd" in row or not hasattr(entry, "as_text"):
                    continue
                row["spmd"] = _spmd.analyze_compiled(
                    entry, label=row.get("label", ""), kind=row.get("kind", ""),
                    params=self.state.get("params"),
                    param_shardings=psh).summary()
        return rows

    # -- interop -----------------------------------------------------------
    def sync_to_model(self):
        """Write compiled-state params/buffers back into the eager model."""
        for name, p in self.model.named_parameters():
            p._value = self.state["params"][name]
        for name, b in self.model.named_buffers():
            b._value = self.state["buffers"][name]

    def state_dict(self):
        return self.state

    def set_state(self, state):
        self.state = state

    def compile(self, sample_inputs, sample_labels):
        """AOT-compile and return the cost/compile stats (parity: first-run
        Convert+compile in interpretercore)."""
        inputs = tuple(jnp.asarray(unwrap(x)) for x in (sample_inputs if isinstance(sample_inputs, (list, tuple)) else [sample_inputs]))
        labels = tuple(jnp.asarray(unwrap(y)) for y in (sample_labels if isinstance(sample_labels, (list, tuple)) else [sample_labels]))
        lowered = self._jit.lower(self.state, (inputs, labels))
        compiled = lowered.compile()
        return compiled


class MultiStepRunner:
    """Amortized training driver over a batch stream: groups every K batches
    into one device-resident stack and runs them through
    :meth:`TrainStep.run_steps` — one Python/XLA dispatch per K steps, the
    JAX/XLA production-trainer idiom (device data + lax.scan, host sync only
    at log boundaries).

    ``batch_iter`` yields per-step ``(inputs, labels)`` batches (a plain
    ``io.DataLoader`` works); with ``prestacked=True`` it yields
    ``[k, ...]``-stacked pairs (``io.DataLoader(fuse_steps=k)``), skipping
    the host-side stacking here. Iterating the runner yields one stacked
    metrics dict per dispatch; a trailing group smaller than K still runs
    (one extra specialization compile for that size).

    ``monitor`` (a :class:`paddle_tpu.stability.HealthMonitor`) makes the
    runner health-aware: every dispatch's stacked metrics are fed to the
    monitor, which handles periodic checkpointing and divergence rollback
    (restoring ``step.state`` in place — the stream just keeps going with
    the rewound state). One observe per K steps: the guard's no-per-step-
    sync property is preserved.
    """

    def __init__(self, step: TrainStep, k: int, prestacked: bool = False,
                 monitor=None):
        if int(k) < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.step = step
        self.k = int(k)
        self.prestacked = prestacked
        self.monitor = monitor
        if monitor is not None and monitor.train_step is None:
            monitor.train_step = step

    def _emit(self, metrics):
        if self.monitor is not None:
            self.monitor.observe(metrics)
        return metrics

    def run(self, batch_iter):
        if self.prestacked:
            for stacked in batch_iter:
                lead = jax.tree_util.tree_leaves(stacked)[0].shape[0]
                yield self._emit(self.step.run_steps(tuple(stacked), k=lead))
            return
        group = []
        for batch in batch_iter:
            group.append(batch)
            if len(group) == self.k:
                yield self._emit(self.step.run_steps(group))
                group = []
        if group:
            yield self._emit(self.step.run_steps(group))

    __call__ = run


class EvalStep:
    """Compiled forward-only step.

    With ``mesh``, parameters are placed per their ``dist_spec`` annotations
    and inputs are batch-sharded over dp×sdp — sharded evaluation, the
    counterpart of fleet.distributed_step for inference/eval loops.
    """

    def __init__(self, model, mesh=None, batch_sharding=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.model = model
        self.mesh = mesh

        def _fwd(params, buffers, inputs):
            out, _ = _pure_model_call(model, {**params, **buffers}, inputs, {}, False, None)
            return out

        if mesh is not None:
            param_shardings = {
                name: NamedSharding(mesh, p.dist_spec if getattr(p, "dist_spec", None) is not None else P())
                for name, p in model.named_parameters()
            }
            buf_shardings = {name: NamedSharding(mesh, P()) for name, _ in model.named_buffers()}
            if batch_sharding is None:
                batch_sharding = NamedSharding(mesh, P(("dp", "sdp")))
            self._param_shardings = param_shardings
            self._jit = jax.jit(_fwd, in_shardings=(param_shardings, buf_shardings, batch_sharding))
        else:
            self._param_shardings = None
            self._jit = jax.jit(_fwd)

    def __call__(self, *inputs):
        arrays = tuple(unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x) for x in inputs)
        params = self.model.param_arrays()
        if self._param_shardings is not None:
            # place once per distinct param set — re-device_put per batch was a
            # host round-trip in the eval loop (VERDICT r3). The source dict is
            # held so `is`-identity over every leaf detects swapped params
            # without id-recycling hazards.
            src = getattr(self, "_placed_src", None)
            if src is None or src.keys() != params.keys() or any(
                    src[k] is not params[k] for k in params):
                self._placed = {k: jax.device_put(v, self._param_shardings[k]) for k, v in params.items()}
                self._placed_src = dict(params)
            params = self._placed
        out = self._jit(params, self.model.buffer_arrays(), arrays)
        return _wrap_tree(out)


def _preflight_lint(fn):
    """Run the dy2static pre-flight linter (paddle_tpu.analysis.ast_lint)
    over ``fn`` and surface findings as one UserWarning — BEFORE transpile or
    tracing, so unsupported constructs are reported with file:line instead of
    dying later as an opaque TracerBoolConversionError."""
    from ..analysis.ast_lint import lint_function

    try:
        diags = lint_function(fn)
    except (OSError, TypeError):  # source unavailable (C ext, REPL, …)
        return []
    if diags:
        import warnings

        from ..analysis.diagnostics import format_report

        warnings.warn("to_static(lint=True) pre-flight report for "
                      f"{getattr(fn, '__qualname__', fn)!r}:\n"
                      + format_report(diags), stacklevel=4)
    return diags


def to_static(function=None, input_spec=None, full_graph=True, lint=False, **kwargs):
    """Decorator compiling a Tensor-level function/Layer method with jax.jit.

    Parity: @paddle.jit.to_static including a minimal AST transpile
    (dygraph_to_static/program_translator.py:239): Python ``if``/``while``/
    ``for _ in range(...)`` and ``and``/``or``/``not`` are rewritten to
    runtime dispatchers that execute natively for concrete values and compile
    to lax.cond / lax.while_loop for traced ones (see jit/dy2static.py for
    the supported envelope). Unsupported shapes (returns inside branches,
    tuple-target loops, …) keep their Python semantics; a tensor-dependent
    condition there raises JAX's TracerBoolConversionError. The explicit
    bridges remain first-class: ``paddle.static.nn.cond``,
    ``paddle.static.nn.while_loop`` and ``paddle.static.nn.switch_case``
    work in eager, to_static and static programs alike; ``@jit.not_to_static``
    opts a function out of rewriting.

    ``lint=True`` runs the dy2static pre-flight linter first
    (paddle_tpu.analysis.ast_lint): unsupported constructs are reported with
    source line numbers via ``warnings`` and attached to the returned wrapper
    as ``__lint_report__`` — before any trace can fail.
    """

    def decorate(fn):
        import types

        from ..nn.layer.base import Layer
        from .dy2static import transpile

        if isinstance(fn, Layer):
            model = fn
            fwd = model.forward
            inner = getattr(fwd, "__func__", fwd)
            lint_report = _preflight_lint(inner) if lint else []
            rewritten = transpile(inner)
            if rewritten is not inner:
                model.forward = types.MethodType(rewritten, model)

            @functools.partial(jax.jit, static_argnums=(3,))
            def _fwd(params, buffers, args, training, rng):
                out, new_buffers = _pure_model_call(model, {**params, **buffers}, args, {}, training, rng)
                return out, new_buffers

            @functools.wraps(model.forward)
            def wrapper(*args):
                arrays = tuple(unwrap(a) if isinstance(a, Tensor) else jnp.asarray(a) for a in args)
                rng = _random.split_key() if model.training else None
                out, new_buffers = _fwd(model.param_arrays(), model.buffer_arrays(), arrays, model.training, rng)
                # propagate buffer side effects (BatchNorm running stats)
                for name, b in model.named_buffers():
                    b._value = new_buffers[name]
                return _wrap_tree(out)

            wrapper.__wrapped_layer__ = model
            wrapper.__lint_report__ = lint_report
            return wrapper

        lint_report = _preflight_lint(fn) if lint else []
        fn = transpile(fn)

        @functools.partial(jax.jit)
        def _pure(args):
            with no_grad():
                out = fn(*_wrap_tree(list(args)))
            return unwrap_tree(out)

        @functools.wraps(fn)
        def wrapper(*args):
            arrays = tuple(unwrap(a) if isinstance(a, Tensor) else jnp.asarray(a) for a in args)
            return _wrap_tree(_pure(arrays))

        wrapper.__lint_report__ = lint_report
        return wrapper

    if function is not None:
        return decorate(function)
    return decorate


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: serialized params + executable StableHLO.

    The reference serializes a pruned ProgramDesc + params
    (python/paddle/fluid/dygraph/jit.py). Here: ``<path>.pdparams`` state
    dict always; with ``input_spec``, an executable jax.export artifact
    (``<path>.pdmodel`` + ``<path>.pdiparams`` metadata — the same format
    static.save_inference_model writes) loadable by ``jit.load`` as a
    TranslatedLayer and by ``paddle.inference.create_predictor``.
    """
    import pickle
    from pathlib import Path

    from ..framework.io import save as _save

    model = getattr(layer, "__wrapped_layer__", layer)
    _save(model.state_dict(), path + ".pdparams")
    if input_spec:
        scope = jax.export.SymbolicScope()
        specs, meta_shapes = [], []
        for i, s in enumerate(input_spec):
            shape = tuple(-1 if d is None else int(d) for d in s.shape)
            meta_shapes.append(list(shape))
            dt = jnp.dtype(s.dtype)  # handles str, np.dtype and scalar types
            if any(d < 0 for d in shape):
                spec_str = ",".join(f"d{i}_{j}" if d < 0 else str(d) for j, d in enumerate(shape))
                shape = jax.export.symbolic_shape(spec_str, scope=scope)
            specs.append(jax.ShapeDtypeStruct(shape, dt))

        params, buffers = model.param_arrays(), model.buffer_arrays()

        def _fwd(*args):
            out, _ = _pure_model_call(model, {**params, **buffers}, args, {}, False, None)
            return out

        exported = jax.export.export(jax.jit(_fwd))(*specs)
        Path(path + ".pdmodel").write_bytes(exported.serialize())
        meta = {
            "feed_names": [getattr(s, "name", None) or f"input_{i}" for i, s in enumerate(input_spec)],
            "fetch_names": [f"output_{i}" for i in range(len(exported.out_avals))],
            "feed_shapes": meta_shapes,
            "feed_dtypes": [str(s.dtype) for s in specs],
            # artifact provenance: .pdmodel is serialized StableHLO
            # (jax.export); this pickle sidecar is the legacy metadata format
            "format": "stablehlo",
            "producer": f"paddle_tpu/jax {jax.__version__}",
        }
        Path(path + ".pdiparams").write_bytes(pickle.dumps(meta))
    return path


class TranslatedLayer:
    """Loaded inference layer (reference TranslatedLayer
    python/paddle/fluid/dygraph/io.py:1137): callable like the original
    model, backed by the exported StableHLO artifact."""

    def __init__(self, prefix: str):
        from ..inference import Config, create_predictor

        self._predictor = create_predictor(Config(prefix))
        self.training = False

    def __call__(self, *args):
        arrays = [unwrap(a) if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        outs = self._predictor.run(arrays)
        wrapped = [_wrap_value(jnp.asarray(o)) for o in outs]
        return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)

    forward = __call__

    def explain(self) -> list:
        """Per-specialization XLA cost rows from the backing AOT Predictor."""
        return self._predictor.explain()

    @property
    def backend(self) -> str:
        """The resolved backend the artifact actually runs on."""
        return self._predictor.get_resolved_backend()

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only (reference parity)")


def load(path, **configs):
    """jit.load parity: with a .pdmodel artifact returns a TranslatedLayer;
    otherwise the bare state dict saved by jit.save."""
    import os

    from ..framework.io import load as _load

    if os.path.exists(path + ".pdmodel"):
        return TranslatedLayer(path)
    return _load(path + ".pdparams")


from ..static import InputSpec  # noqa: E402 — one class for jit AND static
from .dy2static import not_to_static  # noqa: E402 — opt-out marker
# (reference: paddle.static.InputSpec is the single spec type both use)


class ProgramTranslator:
    """Singleton toggling @to_static rewriting (reference
    dygraph_to_static/program_translator.py:920 ProgramTranslator.enable)."""

    _instance = None
    enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static=True):
        type(self).enabled = bool(enable_to_static)


def enable_to_static(enable=True):
    ProgramTranslator.get_instance().enable(enable)


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static logging verbosity (reference jit.set_verbosity); recorded
    only — the transpiler emits no logs."""
    ProgramTranslator.verbosity = int(level)


def set_code_level(level=100, also_to_stdout=False):
    ProgramTranslator.code_level = int(level)


class TracedLayer:
    """Legacy trace-based export (reference fluid/dygraph/jit.py TracedLayer):
    trace(layer, inputs) -> (outputs, traced) where traced serves the jitted
    forward and save_inference_model exports it."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._fn = to_static(layer)
        self._example = inputs

    @staticmethod
    def trace(layer, inputs):
        t = TracedLayer(layer, inputs)
        return t._fn(*inputs), t

    def __call__(self, *args):
        return self._fn(*args)

    def save_inference_model(self, path, feed=None, fetch=None):
        from ..static import InputSpec

        specs = [InputSpec(tuple(x.shape), str(x._value.dtype)) for x in self._example]
        return save(self._layer, path, input_spec=specs)
