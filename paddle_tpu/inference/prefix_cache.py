"""Host-side prompt-prefix KV cache: radix-style chunk reuse with LRU
eviction under a byte budget.

Many serving streams share prompt prefixes (system prompts, few-shot
headers). The cache stores the KV segments of CHUNK-aligned prompt
prefixes — one entry per ``[L, 1, H, C, dh]`` chunk, keyed on the token ids
of the WHOLE prefix up to that chunk's end (KV at a position depends on
every earlier token, so the chain key is exact; byte-keys mean no hash
collisions). A new request walks its longest cached chain and the engine
copies each matched chunk into its slot with one compiled
``dynamic_update_slice`` program — no prefill compute, no prefill compile,
no dispatch of the trunk for the shared portion (the vLLM/SGLang
prefix-caching discipline on the static-cache engine).

Entries are device arrays; eviction is LRU over whole chunks so the budget
(``prefix_cache_mb``) bounds device memory exactly. A chunk is only ever
stored once per distinct prefix chain; re-matching refreshes recency.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache"]


def _seg_bytes(seg) -> Optional[int]:
    """Actual stored bytes of one cache segment leaf-by-leaf: a plain array,
    or an int8 pack ``{"q", "s"}`` (payload + scale planes). The budget math
    must follow the STORED representation — under a quantized KV cache the
    compute-dtype estimate overstates entries ~3-4x and would starve the
    cache of capacity it really has. Returns None for a non-array payload
    (callers fall back to their a-priori estimate)."""
    if isinstance(seg, dict):
        parts = [_seg_bytes(v) for v in seg.values()]
        return None if any(p is None for p in parts) else sum(parts)
    if not (hasattr(seg, "size") and hasattr(seg, "dtype")):
        return None
    return int(seg.size) * int(np.dtype(seg.dtype).itemsize)


class PrefixCache:
    """LRU cache of chunk-aligned prompt-prefix KV segments.

    ``chunk`` is the token granularity (the engine's ``prefill_chunk``);
    ``budget_bytes`` caps the summed device bytes of the stored segments;
    ``entry_bytes`` is the caller's a-priori estimate of one chunk's K+V
    segment (capacity planning before any entry exists) — admission and
    eviction are accounted against each entry's ACTUAL stored bytes.
    """

    def __init__(self, chunk: int, budget_bytes: int, entry_bytes: int):
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.chunk = int(chunk)
        self.budget_bytes = int(budget_bytes)
        self.entry_bytes = int(entry_bytes)
        self._entries: "OrderedDict[bytes, Tuple]" = OrderedDict()
        self._sizes: dict = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------------ keys
    def key(self, prompt: np.ndarray, i: int) -> bytes:
        """Chain key of chunk ``i``: the token ids of the whole prefix up to
        and including that chunk (positions [0, (i+1)*chunk))."""
        return np.ascontiguousarray(prompt[: (i + 1) * self.chunk], np.int32).tobytes()

    # ----------------------------------------------------------------- match
    def match(self, prompt: np.ndarray, max_tokens: int) -> List[Tuple]:
        """Longest chain of cached chunks covering at most ``max_tokens``
        prompt tokens (callers cap at n-1 so the last prompt token always
        runs through the model — logits are not cached). Returns the chunk
        entries ``[(seg_k, seg_v), ...]`` in position order and refreshes
        their LRU recency."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        k = 0
        while (k + 1) * self.chunk <= max_tokens and self.key(prompt, k) in self._entries:
            k += 1
        out = []
        for i in range(k):
            key = self.key(prompt, i)
            self._entries.move_to_end(key)
            out.append(self._entries[key])
        if k:
            self.hits += 1
        else:
            self.misses += 1
        return out

    def has(self, key: bytes) -> bool:
        return key in self._entries

    # ------------------------------------------------------------------- put
    def put(self, key: bytes, seg_k, seg_v) -> bool:
        """Insert one chunk segment under its chain key; evicts LRU entries
        until the byte budget holds. A segment that alone exceeds the budget
        is not stored (the cache never over-commits device memory). Sizes
        come from the segments actually handed in, so quantized (int8 pack)
        and full-precision entries are both charged honestly."""
        sk, sv = _seg_bytes(seg_k), _seg_bytes(seg_v)
        size = self.entry_bytes if (sk is None or sv is None) else sk + sv
        if size > self.budget_bytes:
            return False
        if key in self._entries:
            self._entries.move_to_end(key)
            return True
        self._entries[key] = (seg_k, seg_v)
        self._sizes[key] = size
        self._bytes += size
        while self._bytes > self.budget_bytes:
            old, _ = self._entries.popitem(last=False)
            self._bytes -= self._sizes.pop(old)
            self.evictions += 1
        return key in self._entries

    # ------------------------------------------------------------- accounting
    def bytes_used(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        self._entries.clear()
        self._sizes.clear()
        self._bytes = 0

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "bytes_used": self.bytes_used(),
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }
