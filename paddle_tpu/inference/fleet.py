"""Fault-tolerant serving fleet: N engine replicas behind a routing front.

One :class:`~.engine.DecodeEngine` serves one process's worth of traffic;
millions of users need N of them — and at N, replica death is a steady
state, not an incident. This module composes the serving tier (PR 6/7
engine + continuous-batching scheduler), the elastic-runtime semantics
(PR 1 ``run_resilient``: death ⇒ drain + requeue on the survivors), and the
AOT executable cache (PR 7/10: restart at ``compiles == 0``) into a fleet
that keeps every accepted request's answer — bitwise — through mid-stream
replica kills:

- **placement** — the front :class:`~.router.Router` places each request by
  prefix-cache affinity (the PrefixCache exact-token-chain byte keys as
  hints: a request sharing a system prompt lands on the replica already
  holding those KV chunks) with load-aware tie-breaking;
- **health** — every replica tick refreshes a heartbeat (published through
  a :class:`~..distributed.resilience.RetryingStore`-wrapped TCPStore when
  ``store=`` is given, so N replicas surviving a flaky store back off with
  full jitter instead of thundering-herding); a tick that overruns
  ``heartbeat_timeout`` (straggler, ``FLAGS_chaos_replica_slow_ms``) or
  raises (``FLAGS_chaos_replica_kill_at``, a real fault) marks the replica
  **dead**;
- **drain + requeue** — a dead replica's in-flight requests requeue onto
  survivors from the fleet's own records (original prompt, seed, remaining
  deadline). Completions are **exactly-once**: a request's tokens are
  delivered only when some replica finishes it, and the replay re-prefills
  from the original prompt, so — sampling seeds folding on absolute
  position, never on slot or replica — the replayed tokens are
  bitwise-identical to an unkilled run. Nothing is emitted twice, nothing
  is lost;
- **graceful degradation** — per-request deadlines (expired requests free
  their slot mid-decode, see the scheduler's cancel path) and queue-depth
  admission control: past ``max_queue_depth`` queued requests the fleet
  sheds with a structured :class:`FleetOverloadError` instead of queueing
  without bound;
- **elastic scale-out** — :meth:`ServingFleet.scale_out` adds replicas
  live; with ``FLAGS_compile_cache_dir`` warm, the new replica's whole
  program family loads from the AOT cache and it serves its first request
  at ``infer.compiles == 0``.

Telemetry: ``fleet.*`` counters/gauges (pre-declared in
``observability.metrics.FLEET_COUNTERS``), ``fleet`` run-log events
(membership / replica_dead / requeue / shed / deadline / scale_out /
finished), and an ``observability report`` fleet section.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis import sanitizer as _sanitizer
from ..observability import exporter as _exporter
from ..observability import flightrec as _flightrec
from ..observability import runlog as _runlog
from ..observability import slo as _slo
from ..observability import trace as _trace
from ..observability.metrics import counter_inc, gauge_set, observe
from ..testing import chaos
from .router import Router
from .scheduler import ContinuousBatchingScheduler

__all__ = ["ServingFleet", "EngineReplica", "FleetRequest",
           "FleetOverloadError", "FleetDrainedError", "retry_after_estimate"]


def retry_after_estimate(depth: int, rate: Optional[float],
                         lo: float = 0.5, hi: float = 30.0) -> float:
    """How long a shed client should wait before retrying: queue depth ÷
    recent finish rate (seconds until the backlog plausibly drains),
    clamped to ``[lo, hi]``. With no finish history yet (``rate`` None or
    0) an overloaded fleet answers the pessimistic ``hi`` — better to
    overshoot the wait than to invite an immediate re-shed."""
    if rate is None or rate <= 0:
        est = hi if depth > 0 else lo
    else:
        est = depth / rate
    return float(min(hi, max(lo, est)))


class FleetOverloadError(RuntimeError):
    """Structured load-shed: the fleet's queues are at capacity and this
    request was REJECTED at admission (nothing was enqueued). Callers
    retry with backoff or surface a 429-style answer; ``queued``/``limit``/
    ``replicas_alive`` say how overloaded the fleet was and
    ``retry_after_s`` (queue depth ÷ recent finish rate, clamped — see
    :func:`retry_after_estimate`) is the backoff hint the ingress forwards
    as the ``Retry-After`` header."""

    def __init__(self, queued: int, limit: int, replicas_alive: int,
                 retry_after_s: Optional[float] = None):
        self.queued = int(queued)
        self.limit = int(limit)
        self.replicas_alive = int(replicas_alive)
        self.retry_after_s = (None if retry_after_s is None
                              else float(retry_after_s))
        hint = ("" if self.retry_after_s is None
                else f"; retry after {self.retry_after_s:.1f}s")
        super().__init__(
            f"fleet overloaded: {queued} requests queued >= limit {limit} "
            f"across {replicas_alive} alive replica(s); request shed{hint}")


class FleetDrainedError(RuntimeError):
    """Every replica is dead: the fleet cannot serve or requeue. In-flight
    requests at the time of the last death are listed by fleet id."""

    def __init__(self, lost: List[int]):
        self.lost = list(lost)
        super().__init__(f"fleet: all replicas dead; {len(lost)} in-flight "
                         f"request(s) cannot be requeued: {lost}")


class FleetRequest:
    """The fleet's own record of one accepted request — the source of truth
    for requeueing (the dead replica's bookkeeping is treated as lost) and
    the exactly-once completion ledger (``tokens`` is written once, by the
    replica that finishes the request)."""

    __slots__ = ("fid", "prompt", "max_new_tokens", "eos_token_id", "seed",
                 "deadline_s", "trace_id", "status", "tokens", "replica",
                 "attempts", "submitted_ts", "first_token_ts", "finished_ts")

    def __init__(self, fid: int, prompt, max_new_tokens: int,
                 eos_token_id: Optional[int], seed: int,
                 deadline_s: Optional[float],
                 trace_id: Optional[str] = None):
        self.fid = fid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.seed = int(seed)
        self.deadline_s = deadline_s
        self.trace_id = trace_id      # one id, submit through delivery
        self.status = "queued"
        self.tokens: List[int] = []
        self.replica: Optional[int] = None    # current/last placement
        self.attempts = 1                     # 1 + requeues
        self.submitted_ts = time.perf_counter()
        self.first_token_ts: Optional[float] = None
        self.finished_ts: Optional[float] = None

    @property
    def total_seconds(self):
        return None if self.finished_ts is None else self.finished_ts - self.submitted_ts

    @property
    def ttft_seconds(self):
        return None if self.first_token_ts is None else self.first_token_ts - self.submitted_ts

    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens, the served completion."""
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])


class EngineReplica:
    """One serving replica: a DecodeEngine + its continuous-batching
    scheduler, plus the liveness bookkeeping the fleet's health tracking
    reads (tick count, last tick duration, heartbeat timestamp)."""

    def __init__(self, rid: int, model, engine_kwargs: Dict[str, Any],
                 on_beat=None, keep_finished: int = 256):
        from .engine import DecodeEngine

        self.rid = int(rid)
        self.engine = DecodeEngine(model, **engine_kwargs)
        self.scheduler = ContinuousBatchingScheduler(self.engine,
                                                     keep_finished=keep_finished)
        self.alive = True
        self.death_reason: Optional[str] = None
        self.ticks = 0                # scheduler ticks served
        self.completed = 0            # requests finished on this replica
        self.last_tick_seconds = 0.0
        self.last_beat = time.monotonic()
        self._on_beat = on_beat       # e.g. publish to a TCPStore

    def load(self) -> int:
        """In-flight requests: queued + prefilling + decoding."""
        s = self.scheduler
        return len(s.queue) + len(s.prefilling) + len(s.running)

    def tick(self):
        """One scheduler tick, with the chaos seams the fleet tests drive:
        injected per-tick latency first (a straggler the heartbeat tracker
        must notice), then the armed kill (raises ``ChaosCrash`` — replica
        death, exactly the shape of a real mid-dispatch fault). Returns the
        requests finished this tick."""
        t0 = time.monotonic()
        slow = chaos.replica_slow_ms(self.rid)
        if slow > 0:
            time.sleep(slow / 1e3)
        if chaos.replica_kill_due(self.rid, self.ticks):
            raise chaos.ChaosCrash(
                f"chaos: replica {self.rid} killed after tick {self.ticks}")
        finished = self.scheduler.step()
        self.ticks += 1
        self.last_tick_seconds = time.monotonic() - t0
        self.last_beat = time.monotonic()
        if self._on_beat is not None:
            self._on_beat(self.rid)
        return finished


class ServingFleet:
    """N engine replicas behind a prefix-affinity router, with kill-safe
    drain/requeue, deadlines, and load shedding.

    ``model`` and every ``engine_kwargs`` knob are shared by all replicas
    (identical engine fingerprints — so one warm ``FLAGS_compile_cache_dir``
    serves the whole fleet's program family, and a scale-out replica boots
    at ``infer.compiles == 0``). That pass-through covers the round-3 speed
    knobs too: ``draft=``/``spec_k=`` (each replica builds the same draft
    weights from ``draft_seed``, so a request requeued off a killed replica
    re-accepts the same speculative runs bitwise) and ``kv_dtype="int8"``. ``max_queue_depth`` bounds the TOTAL queued
    (not-yet-admitted) requests across alive replicas; past it
    :meth:`submit` sheds with :class:`FleetOverloadError`.

    ``heartbeat_timeout`` (seconds; 0 disables) declares a replica dead
    when a tick overruns it — the straggler/zombie detector
    (``FLAGS_chaos_replica_slow_ms`` proves it). Ticks that compiled a new
    program — or loaded one from the AOT disk cache — are exempt (a
    warm-up pause is readiness, not liveness: a cold replica must not be
    reaped for booting). A tick that *raises*
    (``FLAGS_chaos_replica_kill_at``, or any real fault) is death
    regardless. ``store=`` additionally publishes per-replica heartbeats to
    a TCPStore through ``RetryingStore`` (full-jitter backoff — see
    ``FLAGS_store_retry_jitter``) so an external supervisor can watch
    membership the elastic way.

    Driving: :meth:`submit` then :meth:`step` per tick (or :meth:`run` to
    drain). All replicas tick in-process; the fleet survives any of them
    dying mid-stream, requeueing their in-flight requests onto survivors
    with exactly-once, bitwise-identical completions.
    """

    _HB_PREFIX = "fleet_serve/hb"

    def __init__(self, model, replicas: int = 2, *,
                 max_queue_depth: int = 64, heartbeat_timeout: float = 0.0,
                 store=None, affinity_load_slack: int = 2,
                 keep_finished: int = 256, **engine_kwargs):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if keep_finished < 1:
            raise ValueError(f"keep_finished must be >= 1, got {keep_finished}")
        self.model = model
        self.engine_kwargs = dict(engine_kwargs)
        self.max_queue_depth = int(max_queue_depth)
        self.keep_finished = int(keep_finished)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.router = Router(chunk=engine_kwargs.get("prefill_chunk"),
                             affinity_load_slack=affinity_load_slack)
        self._store = None
        if store is not None:
            from ..distributed.resilience import RetryingStore

            self._store = store if isinstance(store, RetryingStore) else RetryingStore(store)  # noqa: PTA104 (host-side serving loop, never traced)
        self.replicas: Dict[int, EngineReplica] = {}
        # the fleet ledger: delivered (terminal) requests are GC'd past
        # keep-last-k each tick — in-flight entries are never evicted, so
        # exactly-once + kill/requeue accounting is untouched
        self.requests: Dict[int, FleetRequest] = {}
        self.finished_total = 0       # completions ever, across ledger GC
        self._inflight: Dict[int, Dict[int, int]] = {}  # rid -> {local rid: fid}
        self._next_fid = 0
        self._next_rid = 0
        self.requeues = 0
        # recent completion timestamps (monotonic) — the finish-rate window
        # behind FleetOverloadError.retry_after_s and the ingress backoff
        self._finish_times: collections.deque = collections.deque(maxlen=64)
        # cascade-death bookkeeping: _on_replica_death is re-entrant (a
        # survivor can die while absorbing requeued work — _place runs a
        # synchronous submit); the outermost call owns the drain loop
        self._requeue_backlog: List[int] = []
        self._draining = False
        for _ in range(int(replicas)):
            self._add_replica()
        self._emit_membership()
        # live export (FLAGS_metrics_port; no-op at the default 0): the
        # fleet driver is exactly the long-lived process /metrics exists for
        _exporter.register_health("fleet", self._health)
        _exporter.ensure_started(store=self._store)

    # ------------------------------------------------------------ replicas
    def _health(self) -> dict:
        """The /healthz probe: fleet liveness is replica liveness."""
        alive = sorted(self._alive())
        dead = sorted(set(self.replicas) - set(alive))
        return {"ok": bool(alive), "replicas_alive": alive,
                "replicas_dead": dead, "queue_depth": self.queue_depth()}

    def _beat(self, rid: int) -> None:
        self._store.set(f"{self._HB_PREFIX}/{rid}", repr(time.time()))

    def _add_replica(self) -> EngineReplica:
        rid = self._next_rid
        self._next_rid += 1
        rep = EngineReplica(rid, self.model, self.engine_kwargs,
                            on_beat=self._beat if self._store is not None else None,
                            keep_finished=self.keep_finished)
        self.replicas[rid] = rep
        self._inflight[rid] = {}
        if self._store is not None:
            self._beat(rid)
        return rep

    def _alive(self) -> Dict[int, EngineReplica]:
        return {rid: rep for rid, rep in self.replicas.items() if rep.alive}

    def _emit_membership(self) -> None:
        alive = sorted(self._alive())
        dead = sorted(set(self.replicas) - set(alive))
        gauge_set("fleet.replicas_alive", len(alive))
        gauge_set("fleet.replicas_dead", len(dead))
        _runlog.emit("fleet", kind="membership", component="fleet",
                     alive=alive, dead=dead)

    def membership(self) -> Dict[int, float]:
        """Store-published heartbeat ages (seconds) per replica — what an
        EXTERNAL supervisor sees. Requires ``store=``."""
        if self._store is None:
            raise RuntimeError("fleet: no store configured for membership")
        now = time.time()
        out = {}
        for rid in self.replicas:
            try:
                ts = float(self._store.get(f"{self._HB_PREFIX}/{rid}", timeout=0.25))
                out[rid] = now - ts  # noqa: PTA104 (host-side serving loop, never traced)
            except (TimeoutError, ValueError, OSError):
                out[rid] = float("inf")  # noqa: PTA104 (host-side serving loop, never traced)
        return out

    def scale_out(self, n: int = 1) -> List[int]:
        """Add ``n`` replicas live. With a warm ``FLAGS_compile_cache_dir``
        the new replicas' program family loads from the AOT executable cache
        — first token at ``infer.compiles == 0`` (the bench's
        ``scaleout_ttft_ms``)."""
        new = [self._add_replica().rid for _ in range(int(n))]
        counter_inc("fleet.scale_outs", len(new))
        _runlog.emit("fleet", kind="scale_out", component="fleet", replicas=new)
        self._emit_membership()
        return new

    def kill_replica(self, rid: int, reason: str = "killed") -> None:
        """Administratively kill a replica (tests/bench: the direct form of
        the chaos kill). Its in-flight requests drain onto the survivors."""
        rep = self.replicas[rid]
        if rep.alive:
            self._on_replica_death(rep, RuntimeError(reason))

    # ----------------------------------------------------------- admission
    def queue_depth(self) -> int:
        """Queued (not yet admitted) requests across alive replicas — the
        number admission control compares against ``max_queue_depth``."""
        return sum(len(rep.scheduler.queue) for rep in self._alive().values())

    def finish_rate(self) -> Optional[float]:
        """Recent completions per second over the sliding finish window
        (None until two completions exist) — the denominator of
        :func:`retry_after_estimate`."""
        t = self._finish_times
        if len(t) < 2 or t[-1] <= t[0]:
            return None
        return (len(t) - 1) / (t[-1] - t[0])

    def transport_lag(self) -> Dict[str, float]:
        """Transport-health watermarks the ingress reads for backpressure.
        The in-process fleet has no wire: backlog is always 0 and the beat
        age is the slowest alive replica's last tick duration (a straggler
        shows up here exactly like a laggy socket would)."""
        alive = [rep for rep in self.replicas.values() if rep.alive]
        beat = max((rep.last_tick_seconds for rep in alive), default=0.0)
        return {"out_backlog": 0.0, "beat_age_s": float(beat)}

    def submit(self, prompt, max_new_tokens: int = 16,
               eos_token_id: Optional[int] = None, seed: int = 0,
               deadline_s: Optional[float] = None,
               replica: Optional[int] = None) -> int:
        """Route one prompt into the fleet; returns the fleet request id.

        Admission control runs FIRST: at ``max_queue_depth`` queued requests
        the fleet sheds with :class:`FleetOverloadError` (structured — the
        caller can back off) instead of queueing without bound. Placement is
        prefix-affinity with load tie-breaking; ``replica=`` pins it (tests,
        targeted warm-up). ``deadline_s`` bounds total time from THIS
        submission — it survives requeues (the remaining budget rides
        along), and an expired request frees its slot mid-decode."""
        alive = self._alive()
        if not alive:
            raise FleetDrainedError(sorted(
                fid for fid, r in self.requests.items()
                if r.status in ("queued", "prefilling", "running")))
        depth = self.queue_depth()
        if depth >= self.max_queue_depth:
            counter_inc("fleet.sheds")
            _runlog.emit("fleet", kind="shed", component="fleet",
                         queued=depth, limit=self.max_queue_depth)
            raise FleetOverloadError(
                depth, self.max_queue_depth, len(alive),
                retry_after_s=retry_after_estimate(depth, self.finish_rate()))
        if replica is not None:
            if replica not in alive:
                raise ValueError(f"replica {replica} is not alive")
            rid, reason = int(replica), "pinned"
        else:
            rid, reason = self.router.place(
                prompt, {r: rep.load() for r, rep in alive.items()})
            counter_inc("fleet.routed_affinity" if reason == "affinity"
                        else "fleet.routed_load")
        fid = self._next_fid
        self._next_fid += 1
        freq = FleetRequest(fid, prompt, max_new_tokens, eos_token_id, seed,
                            deadline_s, trace_id=_trace.new_trace_id("fleet"))
        self.requests[fid] = freq
        _runlog.emit("fleet", kind="submitted", component="fleet", id=fid,
                     trace=freq.trace_id, prompt_tokens=len(freq.prompt),
                     max_new_tokens=freq.max_new_tokens)
        self._place(freq, rid, reason)
        counter_inc("fleet.requests_submitted")
        gauge_set("fleet.queue_depth", self.queue_depth())
        return fid

    def _place(self, freq: FleetRequest, rid: int, reason: str,
               deadline_s: Optional[float] = "unset") -> None:
        """Submit ``freq`` to replica ``rid``'s scheduler and index the
        local rid so completions map back to the fleet ledger."""
        rep = self.replicas[rid]
        if deadline_s == "unset":
            deadline_s = freq.deadline_s
        local = rep.scheduler.submit(
            freq.prompt, max_new_tokens=freq.max_new_tokens,
            eos_token_id=freq.eos_token_id, seed=freq.seed,
            deadline_s=deadline_s, trace_id=freq.trace_id)
        self.router.register(freq.prompt, rid)
        freq.replica = rid
        freq.status = "running"
        self._inflight[rid][local] = freq.fid
        _runlog.emit("fleet", kind="placed", component="fleet", id=freq.fid,
                     replica=rid, reason=reason, attempt=freq.attempts,
                     trace=freq.trace_id)

    def _local_rid(self, fid: int) -> Optional[int]:
        """The scheduler-local rid currently running fleet request ``fid``
        (None when it is not in flight on any replica)."""
        freq = self.requests.get(fid)
        if freq is None or freq.replica is None:
            return None
        for local, f in self._inflight.get(freq.replica, {}).items():  # noqa: PTA102 (host-side serving transport, never traced)
            if f == fid:
                return local  # noqa: PTA101 (host-side serving transport, never traced)
        return None

    def tokens_so_far(self, fid: int) -> List[int]:
        """Live view of ``fid``'s generated tokens — the ledger's copy once
        terminal, the owning scheduler's in-progress run while decoding.
        The ingress streams from this without waiting for completion."""
        freq = self.requests[fid]
        if freq.status not in self._TERMINAL:
            local = self._local_rid(fid)
            if local is not None:
                r = self.replicas[freq.replica].scheduler.find(local)
                if r is not None:
                    return list(r.tokens)
        return list(freq.tokens)

    def cancel(self, fid: int, status: str = "cancelled") -> bool:
        """Cancel one in-flight request (client went away, deadline raced):
        frees its scheduler slot mid-decode and marks the ledger terminal.
        False when the request is unknown or already terminal."""
        freq = self.requests.get(fid)
        if freq is None or freq.status in self._TERMINAL:
            return False
        local = self._local_rid(fid)
        if local is None:
            return False
        rep = self.replicas[freq.replica]
        if not (rep.alive and rep.scheduler.cancel(local, status=status)):
            return False
        self._inflight[freq.replica].pop(local, None)
        freq.status = status
        freq.finished_ts = time.perf_counter()
        counter_inc("fleet.cancels")
        _runlog.emit("fleet", kind="cancelled", component="fleet", id=fid,
                     replica=freq.replica, status=status, trace=freq.trace_id)
        return True

    # ----------------------------------------------------------- the loop
    def step(self) -> List[FleetRequest]:
        """One fleet tick: advance every alive replica one scheduler tick,
        harvest completions/cancellations into the fleet ledger, and answer
        replica faults (raise or heartbeat overrun) with mark-dead + drain +
        requeue. Returns the fleet requests finished this tick."""
        done: List[FleetRequest] = []
        for rid, rep in list(self.replicas.items()):  # noqa: PTA102 (host-side serving loop, never traced)
            if not rep.alive:
                continue
            from ..observability.metrics import counters as _counters

            def _builds():
                c = _counters("infer.")
                return (c.get("infer.compiles", 0)
                        + c.get("infer.aot_cache_hits", 0))

            builds0 = _builds()
            try:
                finished = rep.tick()
            except Exception as exc:  # replica death: chaos kill or real fault
                self._on_replica_death(rep, exc)
                continue  # noqa: PTA103 (host-side serving loop, never traced)
            self._harvest(rep, finished, done)
            compiled = _builds() > builds0
            if (self.heartbeat_timeout and not compiled
                    and rep.last_tick_seconds > self.heartbeat_timeout):
                # the tick came back but took longer than the liveness
                # window — to the fleet this replica's heartbeat went dark
                # (straggler/zombie); same protocol as a death. Ticks that
                # compiled or AOT-loaded a program are exempt: a warm-up
                # pause is a readiness matter, not a liveness one.
                self._on_replica_death(rep, TimeoutError(
                    f"heartbeat lost: tick took {rep.last_tick_seconds:.3f}s "
                    f"> timeout {self.heartbeat_timeout:g}s"))
        self._gc_ledger(protect={r.fid for r in done})
        if _sanitizer.enabled():
            # runtime PTA305: post-GC the ledger is keep-last-k + in-flight;
            # anything past twice that means the GC stopped working
            _sanitizer.note_ledger(
                "fleet", "requests", len(self.requests),
                bound=2 * self.keep_finished + self.max_queue_depth)
        alive = [rep for rep in self.replicas.values() if rep.alive]
        if alive:
            # in the in-process fleet the last tick's duration IS the
            # heartbeat age: a straggling replica shows up as a long tick
            gauge_set("fleet.heartbeat_staleness_seconds",
                      max(rep.last_tick_seconds for rep in alive))
        _slo.on_tick()  # judgment layer: single flag check until armed
        return done

    _TERMINAL = ("finished", "cancelled", "deadline_exceeded")

    def _gc_ledger(self, protect=()) -> None:
        """Keep-last-k GC of delivered requests: evict the OLDEST terminal
        entries past ``keep_finished`` (fids are monotonic, so dict order is
        submission order). In-flight entries are never touched — requeue and
        exactly-once delivery read the ledger only for live fids — and THIS
        tick's completions are protected so :meth:`step`'s return is always
        harvestable before eviction."""
        protect = set(protect)
        terminal = [fid for fid, r in self.requests.items()
                    if r.status in self._TERMINAL and fid not in protect]
        overflow = len(terminal) - self.keep_finished
        for fid in terminal[:max(0, overflow)]:
            del self.requests[fid]

    def _harvest(self, rep: EngineReplica, finished, done: List[FleetRequest]):
        inflight = self._inflight[rep.rid]
        for r in finished:
            fid = inflight.pop(r.rid, None)
            if fid is None:
                continue
            freq = self.requests[fid]
            # the exactly-once seam: tokens are written here and only here,
            # by the single replica that ran this request to completion
            freq.tokens = list(r.tokens)  # noqa: PTA104 (host-side serving loop, never traced)
            freq.status = "finished"  # noqa: PTA104 (host-side serving loop, never traced)
            freq.finished_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop, never traced)
            if r.first_token_ts is not None:
                freq.first_token_ts = r.first_token_ts  # noqa: PTA104 (host-side serving loop, never traced)
            rep.completed += 1  # noqa: PTA104 (host-side serving loop, never traced)
            self.finished_total += 1  # noqa: PTA104 (host-side serving loop)
            self._finish_times.append(time.monotonic())  # noqa: PTA104, PTA305 (host-side, never traced; deque bounded at maxlen=64)
            counter_inc("fleet.requests_completed")
            observe("fleet.latency_seconds", freq.total_seconds)
            _runlog.emit("fleet", kind="finished", component="fleet",
                         id=fid, replica=rep.rid, new_tokens=len(freq.tokens),
                         seconds=freq.total_seconds, attempts=freq.attempts,
                         trace=freq.trace_id)
            done.append(freq)  # noqa: PTA104 (host-side serving loop, never traced)
        for local in [l for l in list(inflight) if l in rep.scheduler.cancelled]:
            fid = inflight.pop(local)
            freq = self.requests[fid]
            freq.status = rep.scheduler.cancelled[local].status  # noqa: PTA104 (host-side serving loop, never traced)
            freq.finished_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop, never traced)
            if freq.status == "deadline_exceeded":
                counter_inc("fleet.deadline_hits")
            _runlog.emit("fleet",
                         kind=("deadline" if freq.status == "deadline_exceeded"
                               else "cancelled"),
                         component="fleet", id=fid,
                         replica=rep.rid, status=freq.status,
                         trace=freq.trace_id)

    def _on_replica_death(self, rep: EngineReplica, exc: BaseException) -> None:
        """Mark dead, forget chains, requeue in-flight work. Re-entrant:
        requeue placement can kill the survivor it lands on (its scheduler
        submit runs synchronously), re-entering this method mid-drain. A
        nested call parks the newly dead replica's fids on the shared
        backlog and returns; the OUTERMOST call keeps draining until the
        backlog is empty, so a cascade (every survivor dying in turn)
        still raises one FleetDrainedError accounting for every lost fid
        — the single-pass version dropped the outer pending set."""
        if not rep.alive:
            return
        rep.alive = False
        rep.death_reason = f"{type(exc).__name__}: {exc}"
        counter_inc("fleet.replica_deaths")
        self.router.forget_replica(rep.rid)
        pending = self._inflight.pop(rep.rid, {})
        self._inflight[rep.rid] = {}
        lost_traces = sorted({t for t in (
            self.requests[fid].trace_id for fid in pending.values())
            if t is not None})
        _runlog.emit("fleet", kind="replica_dead", component="fleet",
                     replica=rep.rid, reason=rep.death_reason,
                     inflight=len(pending), traces=lost_traces)
        _flightrec.dump("replica_death", exc, replica=rep.rid,
                        inflight=sorted(pending.values()),
                        traces=lost_traces)
        self._emit_membership()
        self._requeue_backlog.extend(sorted(pending.values()))
        if self._draining:
            return  # nested death: the outermost drain loop absorbs it
        self._draining = True
        try:
            lost: List[int] = []
            while self._requeue_backlog:
                fid = self._requeue_backlog.pop(0)
                survivors = self._alive()  # recomputed: the set shrinks mid-drain
                if not survivors:
                    lost.append(fid)  # noqa: PTA104 (host-side serving loop, never traced)
                    continue
                self._requeue(self.requests[fid], survivors)
            if lost:
                raise FleetDrainedError(sorted(lost))
        finally:
            self._draining = False

    def _requeue(self, freq: FleetRequest, survivors: Dict[int, EngineReplica]):
        """Re-place one request lost to a replica death. The replay runs the
        ORIGINAL prompt with the ORIGINAL seed — sampling keys fold on the
        request seed and absolute position, never on slot or replica, so the
        replayed tokens are bitwise what the dead replica would have
        produced. The remaining deadline budget rides along; a request whose
        deadline already passed is expired here instead of replayed."""
        remaining = freq.deadline_s
        if freq.deadline_s is not None:
            remaining = freq.deadline_s - (time.perf_counter() - freq.submitted_ts)
            if remaining <= 0:
                freq.status = "deadline_exceeded"  # noqa: PTA104 (host-side serving loop, never traced)
                freq.finished_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop, never traced)
                counter_inc("fleet.deadline_hits")
                _runlog.emit("fleet", kind="deadline", component="fleet",
                             id=freq.fid, replica=freq.replica,
                             status="deadline_exceeded", trace=freq.trace_id)
                return
        freq.attempts += 1
        self.requeues += 1
        counter_inc("fleet.requeues")
        rid, reason = self.router.place(
            freq.prompt, {r: rep.load() for r, rep in survivors.items()})
        _runlog.emit("fleet", kind="requeue", component="fleet", id=freq.fid,
                     replica=rid, from_replica=freq.replica, reason=reason,
                     trace=freq.trace_id)
        self._place(freq, rid, f"requeue/{reason}", deadline_s=remaining)

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, FleetRequest]:
        """Drive :meth:`step` until every alive replica drains (or
        ``max_ticks``); returns ``{fid: FleetRequest}`` for every completion
        of the run — accumulated across ticks, so requests the keep-last-k
        ledger GC has since evicted are still returned."""
        done = {fid: r for fid, r in self.requests.items()
                if r.status == "finished"}
        ticks = 0
        while any(rep.scheduler.queue or rep.scheduler.prefilling
                  or rep.scheduler.running
                  for rep in self._alive().values()):
            for r in self.step():
                done[r.fid] = r  # noqa: PTA104 (host-side serving loop)
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        done.update({fid: r for fid, r in self.requests.items()
                     if r.status == "finished"})
        return done

    # ------------------------------------------------------------- summary
    def stats(self) -> dict:
        alive = self._alive()
        return {
            "replicas": len(self.replicas),
            "alive": sorted(alive),
            "dead": sorted(set(self.replicas) - set(alive)),
            "requests": len(self.requests),
            "finished": sum(1 for r in self.requests.values()
                            if r.status == "finished"),
            "finished_total": self.finished_total,
            "requeues": self.requeues,
            "queue_depth": self.queue_depth(),
            "router": self.router.stats(),
            "per_replica": {rid: {
                "alive": rep.alive,
                "ticks": rep.ticks,
                "completed": rep.completed,
                "load": rep.load(),
                "death_reason": rep.death_reason,
            } for rid, rep in self.replicas.items()},
        }
