"""Resilient network ingress: the HTTP front door over the serving fleet.

Until now clients called the fleet in-process; this module puts a real
network boundary in front of :class:`~.procfleet.ProcServingFleet` /
:class:`~.fleet.ServingFleet` using nothing but the stdlib HTTP server —
and carries the fleet's hard-won failure semantics through it intact:

- **POST /v1/generate** — JSON in; either a complete JSON answer or a
  chunked-transfer **per-token stream** (one JSON line per chunk) riding
  the same append-only ``FleetRequest.tokens`` ledger as
  :class:`~.procfleet.TokenStream` — so a replica ``kill -9`` mid-stream
  requeues upstream and the HTTP client still receives every token
  exactly once, bitwise-identical to an unkilled run.
- **idempotency keys** — an ``Idempotency-Key`` header (or
  ``idempotency_key`` body field) maps onto the fleet ledger: an
  at-least-once client retry of the same key returns the SAME request's
  result (held by object reference, so ledger GC cannot break it) and
  never double-generates.
- **deadlines** — ``deadline_s`` propagates into the scheduler's deadline
  sweep; an expired request frees its slot mid-decode and answers with
  its terminal status.
- **client disconnect → cancel** — a dropped socket (detected by peeking
  the connection between chunks, or a failed write) cancels the request
  mid-decode through the fleet, freeing its slot for live traffic.
- **backpressure** — admission rejects with structured statuses instead
  of queueing without bound: fleet overload (429 +
  ``Retry-After`` from :func:`~.fleet.retry_after_estimate`), transport
  lag past the watermarks — unacknowledged fast-path backlog or stale
  heartbeats (503), drain in progress (503).
- **graceful drain** — SIGTERM stops admission, flips ``/healthz`` to 503
  (an external LB stops routing first), lets in-flight requests finish
  within ``drain_grace`` (cancelling stragglers), then exits 0.

Fleet mutations are not thread-safe, so a single **driver thread** owns
the fleet: it runs the ``step()`` loop and executes submit/cancel/read
ops posted by HTTP handler threads (each op a closure + completion
event). Handler threads otherwise only READ the ledger objects they were
handed — the same GC-safe object-reference discipline TokenStream uses.

``FLAGS_chaos_ingress_disconnect_at`` makes the disconnect path
deterministic: the ingress force-drops the client connection after N
streamed chunks, which must turn into a mid-decode cancel.
"""
from __future__ import annotations

import json
import queue
import select
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ..observability import runlog as _runlog
from ..observability.metrics import counter_inc, gauge_set, observe
from ..testing import chaos
from .fleet import (FleetDrainedError, FleetOverloadError,
                    retry_after_estimate)

__all__ = ["ServingIngress"]

_TERMINAL = ("finished", "cancelled", "deadline_exceeded")


class _FleetDriver(threading.Thread):
    """The one thread allowed to touch the fleet. Runs ``fleet.step()``
    continuously and executes posted ops between steps; HTTP handler
    threads block on :meth:`call` for their result. A ``FleetDrainedError``
    raised by the step loop (every replica dead) is latched in
    :attr:`dead` so waiting handlers fail over to 503 instead of hanging
    on requests that can never finish."""

    def __init__(self, fleet, poll_s: float = 0.002):
        super().__init__(daemon=True, name="ingress-driver")
        self.fleet = fleet
        self.poll_s = float(poll_s)
        self.ops: "queue.Queue" = queue.Queue()
        self.stop_ev = threading.Event()
        self.dead: Optional[BaseException] = None
        self.lost: set = set()   # fids FleetDrainedError reported unrecoverable

    def call(self, fn, timeout: float = 30.0):
        """Run ``fn()`` on the driver thread; return its result or raise
        its exception here."""
        if not self.is_alive():
            raise RuntimeError("ingress: fleet driver is not running")
        ev = threading.Event()
        box: Dict[str, Any] = {}
        self.ops.put((fn, ev, box))
        if not ev.wait(timeout):
            raise TimeoutError("ingress: fleet driver did not answer")
        if "exc" in box:
            raise box["exc"]
        return box.get("ret")

    def _step(self) -> None:
        try:
            self.fleet.step()
        except FleetDrainedError as exc:
            self.dead = exc
            self.lost.update(exc.lost)

    def run(self) -> None:
        while not self.stop_ev.is_set():
            drained_ops = False
            while True:
                try:
                    fn, ev, box = self.ops.get_nowait()
                except queue.Empty:
                    break  # noqa: PTA103 (host-side serving transport, never traced)
                drained_ops = True
                try:
                    box["ret"] = fn()  # noqa: PTA104 (host-side ingress driver, never traced)
                except BaseException as exc:  # handed to the calling thread
                    box["exc"] = exc  # noqa: PTA104 (host-side ingress driver, never traced)
                ev.set()
            self._step()
            if not drained_ops:
                time.sleep(self.poll_s)


class ServingIngress:
    """Stdlib HTTP/1.1 front door over a serving fleet.

    ::

        fleet = ProcServingFleet(GPTConfig.tiny(), replicas=2, ...)
        with ServingIngress(fleet, port=8080) as ing:
            ing.serve_until_drained()   # SIGTERM => graceful drain, rc 0

    API surface:

    - ``POST /v1/generate`` — body ``{"prompt": [ints],
      "max_new_tokens": n, "eos_token_id": t?, "seed": s?,
      "deadline_s": d?, "stream": bool?, "idempotency_key": k?}``
      (``Idempotency-Key`` header also honored). Non-streaming answers
      one JSON object; ``stream: true`` answers chunked transfer, one
      JSON line per token chunk, then a terminal ``{"done": ...}`` line.
    - ``GET /healthz`` — 200 while accepting, 503 once draining or the
      fleet is dead (flip-first so an external LB stops routing before
      the drain starts cancelling).
    - ``GET /stats`` — fleet + ingress stats as JSON.

    ``backlog_watermark`` / ``beat_watermark_s`` are the transport-lag
    shed thresholds read from ``fleet.transport_lag()``; ``drain_grace``
    bounds how long a SIGTERM drain waits for in-flight requests before
    cancelling them."""

    def __init__(self, fleet, host: str = "127.0.0.1", port: int = 0, *,
                 drain_grace: float = 10.0, backlog_watermark: int = 512,
                 beat_watermark_s: Optional[float] = None,
                 request_timeout: float = 120.0, idem_keep: int = 1024,
                 start: bool = True):
        self.fleet = fleet
        self.host = host
        self.drain_grace = float(drain_grace)
        self.backlog_watermark = int(backlog_watermark)
        self.beat_watermark_s = beat_watermark_s
        self.request_timeout = float(request_timeout)
        self.idem_keep = int(idem_keep)
        self._idem: Dict[str, Any] = {}       # key -> FleetRequest (by ref)
        self._active: set = set()             # fids being served right now
        self._lock = threading.Lock()
        self._draining = False
        self._drain_ev = threading.Event()
        self._stopped = False
        self.exit_code: Optional[int] = None
        self.driver = _FleetDriver(fleet, poll_s=getattr(fleet, "poll_s", 0.002))
        self._server = ThreadingHTTPServer((host, int(port)),
                                           _make_handler(self))
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="ingress-http")
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServingIngress":
        if not self.driver.is_alive():
            self.driver.start()
        if not self._server_thread.is_alive():
            self._server_thread.start()
        _runlog.emit("ingress", kind="started", host=self.host, port=self.port)
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT begin a graceful drain (main thread only)."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self.begin_drain())

    def begin_drain(self) -> None:
        """Flip to NotReady and stop admitting; the actual drain runs in
        :meth:`drain` / :meth:`serve_until_drained`. Safe from a signal
        handler and idempotent."""
        if not self._draining:
            self._draining = True  # noqa: PTA104 (host-side serving transport, never traced)
            counter_inc("ingress.drains")
            _runlog.emit("ingress", kind="drain_begin",
                         inflight=len(self._active))
        self._drain_ev.set()

    def drain(self, grace: Optional[float] = None) -> int:
        """Graceful drain: stop accepting (healthz already 503), wait for
        in-flight requests to finish within ``grace``, cancel stragglers,
        stop the server + driver. Returns the process exit code (0)."""
        self.begin_drain()
        grace = self.drain_grace if grace is None else float(grace)
        t0 = time.monotonic()
        deadline = t0 + grace
        while self._active and time.monotonic() < deadline:
            time.sleep(0.02)
        leftovers = sorted(self._active)
        for fid in leftovers:
            try:
                self.driver.call(lambda f=fid: self.fleet.cancel(f), timeout=5.0)
            except Exception:
                pass  # best-effort: the handler's wait loop still unblocks below
        # give cancelled handlers a moment to flush their terminal response
        deadline = time.monotonic() + 2.0
        while self._active and time.monotonic() < deadline:
            time.sleep(0.02)
        self.stop()
        _runlog.emit("ingress", kind="drain_done",
                     seconds=time.monotonic() - t0,
                     cancelled=len(leftovers))
        self.exit_code = 0
        return 0

    def serve_until_drained(self, install_signals: bool = True) -> int:
        """Block until a drain is requested (SIGTERM/SIGINT or
        :meth:`begin_drain`), run it, return the exit code (0)."""
        if install_signals:
            self.install_signal_handlers()
        while not self._drain_ev.wait(0.2):
            pass
        return self.drain()

    def stop(self) -> None:
        """Immediate teardown (tests; :meth:`drain` calls this last)."""
        if self._stopped:
            return
        self._stopped = True
        self._server.shutdown()
        self._server.server_close()
        self.driver.stop_ev.set()
        self.driver.join(timeout=5.0)

    def __enter__(self) -> "ServingIngress":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # ------------------------------------------------------------ admission
    def _admission_error(self) -> Optional[Dict[str, Any]]:
        """A structured rejection (status/body/retry_after) when the front
        door should not accept right now, else None. Runs in a handler
        thread — only reads."""
        if self._draining:
            return {"status": 503, "error": "draining",
                    "retry_after": self.drain_grace}
        if self.driver.dead is not None:
            return {"status": 503, "error": "fleet_drained",
                    "detail": str(self.driver.dead)}
        try:
            lag = self.fleet.transport_lag()
        except Exception:
            lag = None
        if lag is not None:
            retry = retry_after_estimate(self.fleet.queue_depth(),
                                         self.fleet.finish_rate())
            if lag["out_backlog"] >= self.backlog_watermark:
                counter_inc("ingress.rejected_backpressure")
                return {"status": 503, "error": "transport_backlog",
                        "backlog": lag["out_backlog"], "retry_after": retry}
            if (self.beat_watermark_s is not None
                    and lag["beat_age_s"] >= self.beat_watermark_s):
                counter_inc("ingress.rejected_backpressure")
                return {"status": 503, "error": "transport_stale",
                        "beat_age_s": lag["beat_age_s"], "retry_after": retry}
        return None

    def _submit(self, body: Dict[str, Any], idem_key: Optional[str]):
        """Runs ON the driver thread: idempotency lookup + fleet submit,
        serialized with every other fleet mutation (a concurrent retry of
        the same key cannot double-submit). Returns (freq, replayed)."""
        if idem_key:
            freq = self._idem.get(idem_key)
            if freq is not None:
                counter_inc("ingress.idempotent_hits")
                return freq, True
        fid = self.fleet.submit(
            body["prompt"],
            max_new_tokens=int(body.get("max_new_tokens", 16)),
            eos_token_id=body.get("eos_token_id"),
            seed=int(body.get("seed", 0)),
            deadline_s=body.get("deadline_s"))
        freq = self.fleet.requests[fid]
        if idem_key:
            while len(self._idem) >= self.idem_keep:
                self._idem.pop(next(iter(self._idem)))  # noqa: PTA104 (host-side serving transport, never traced)
            self._idem[idem_key] = freq  # noqa: PTA104 (host-side serving transport, never traced)
        return freq, False

    def _track(self, fid: int, on: bool) -> None:
        with self._lock:
            if on:
                self._active.add(fid)  # noqa: PTA104 (host-side serving transport, never traced)
            else:
                self._active.discard(fid)  # noqa: PTA104 (host-side serving transport, never traced)
        gauge_set("ingress.inflight", len(self._active))

    def _wait_terminal(self, freq, deadline: float) -> None:
        """Poll the ledger object until terminal, the fleet dies, or the
        wall deadline passes (read-only; the driver advances the fleet)."""
        while (freq.status not in _TERMINAL and self.driver.dead is None
                and freq.fid not in self.driver.lost
                and time.monotonic() < deadline):
            time.sleep(0.005)

    def _cancel(self, fid: int) -> None:
        try:
            self.driver.call(lambda: self.fleet.cancel(fid), timeout=5.0)
        except Exception:
            pass

    def stats(self) -> Dict[str, Any]:
        return {"inflight": len(self._active), "draining": self._draining,
                "idempotency_keys": len(self._idem),
                "port": self.port}


# =====================================================================
# the HTTP handler
# =====================================================================

def _make_handler(ingress: ServingIngress):
    """Build the request-handler class bound to ``ingress`` (the stdlib
    server instantiates it per connection; a closure beats globals)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "paddle-tpu-ingress/1.0"

        # silence the default stderr access log; the run log carries it
        def log_message(self, fmt, *args):
            pass

        # ------------------------------------------------------ plumbing
        def _json(self, status: int, doc: Dict[str, Any],
                  retry_after: Optional[float] = None) -> None:
            body = (json.dumps(doc) + "\n").encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                self.send_header("Retry-After", str(max(1, round(retry_after))))
            self.end_headers()
            self.wfile.write(body)

        def _client_gone(self) -> bool:
            """Peek the connection between chunks: a readable socket that
            yields b'' is a closed peer (the request body was already
            consumed, so pending data can only be EOF or pipelining —
            either way the stream should stop)."""
            try:
                r, _, _ = select.select([self.connection], [], [], 0)
                if not r:
                    return False
                return self.connection.recv(1, socket.MSG_PEEK) == b""
            except (OSError, ValueError):
                return True

        # ------------------------------------------------------ endpoints
        def do_GET(self):
            if self.path == "/healthz":
                ok = (not ingress._draining and ingress.driver.dead is None
                      and ingress.driver.is_alive())
                self._json(200 if ok else 503,
                           {"ok": ok, "draining": ingress._draining,
                            "inflight": len(ingress._active)})
            elif self.path == "/stats":
                try:
                    fleet_stats = ingress.driver.call(ingress.fleet.stats)
                except Exception as exc:
                    self._json(503, {"error": str(exc)})
                    return
                self._json(200, {"fleet": fleet_stats,
                                 "ingress": ingress.stats()})
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/v1/generate":
                self._json(404, {"error": "not found"})
                return
            t0 = time.monotonic()
            counter_inc("ingress.requests")
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = body["prompt"]
            except (ValueError, KeyError, TypeError):
                self._json(400, {"error": "bad request: JSON body with "
                                          "'prompt' (list of ints) required"})
                return
            reject = ingress._admission_error()
            if reject is not None:
                if reject["error"] == "draining":
                    counter_inc("ingress.rejected_draining")
                status = reject.pop("status")
                retry = reject.get("retry_after")
                _runlog.emit("ingress", kind="reject", reason=reject["error"],
                             status=status)
                self._json(status, reject, retry_after=retry)
                return
            idem_key = (self.headers.get("Idempotency-Key")
                        or body.get("idempotency_key"))
            try:
                freq, replayed = ingress.driver.call(
                    lambda: ingress._submit(body, idem_key))
            except FleetOverloadError as exc:
                counter_inc("ingress.rejected_overload")
                _runlog.emit("ingress", kind="reject", reason="overload",
                             status=429, queued=exc.queued,
                             retry_after_s=exc.retry_after_s)
                self._json(429, {"error": "overloaded", "queued": exc.queued,
                                 "limit": exc.limit,
                                 "retry_after": exc.retry_after_s},
                           retry_after=exc.retry_after_s)
                return
            except FleetDrainedError as exc:
                _runlog.emit("ingress", kind="reject", reason="fleet_drained",
                             status=503)
                self._json(503, {"error": "fleet_drained", "detail": str(exc)})
                return
            except Exception as exc:
                self._json(500, {"error": f"{type(exc).__name__}: {exc}"})
                return
            _runlog.emit("ingress", kind="request", id=freq.fid,
                         trace=freq.trace_id, stream=bool(body.get("stream")),
                         idempotent=replayed,
                         prompt_tokens=len(freq.prompt))
            ingress._track(freq.fid, True)
            try:
                if body.get("stream"):
                    self._stream(freq, t0)
                else:
                    self._complete(freq, t0)
            finally:
                ingress._track(freq.fid, False)

        # ------------------------------------------------- response modes
        def _deadline(self, freq) -> float:
            wall = ingress.request_timeout
            if freq.deadline_s is not None:
                wall = min(wall, float(freq.deadline_s) + 5.0)
            return time.monotonic() + wall

        def _finish_doc(self, freq) -> Dict[str, Any]:
            if freq.fid in ingress.driver.lost:
                return {"fid": freq.fid, "status": "lost",
                        "error": "fleet_drained"}
            return {"fid": freq.fid, "status": freq.status,
                    "tokens": list(freq.tokens), "attempts": freq.attempts,
                    "trace": freq.trace_id}

        def _complete(self, freq, t0: float) -> None:
            ingress._wait_terminal(freq, self._deadline(freq))
            doc = self._finish_doc(freq)
            if freq.status not in _TERMINAL and doc["status"] != "lost":
                # wall timeout with the request still running: cancel it
                # so the slot frees, answer its terminal state
                ingress._cancel(freq.fid)
                ingress._wait_terminal(freq, time.monotonic() + 5.0)
                doc = self._finish_doc(freq)
            status = 200 if doc["status"] == "finished" else 503
            counter_inc("ingress.responses")
            observe("ingress.request_seconds", time.monotonic() - t0)
            _runlog.emit("ingress", kind="response", id=freq.fid,
                         status=doc["status"], http=status,
                         new_tokens=len(freq.tokens),
                         seconds=time.monotonic() - t0, trace=freq.trace_id)
            self._json(status, doc)

        def _write_chunk(self, payload: bytes) -> None:
            self.wfile.write(b"%x\r\n" % len(payload) + payload + b"\r\n")
            self.wfile.flush()

        def _stream(self, freq, t0: float) -> None:
            """Chunked-transfer stream off the append-only token ledger —
            the HTTP twin of TokenStream's cursor discipline: each poll
            ships the suffix past the cursor, so an upstream requeue
            (which replays bitwise) extends the stream without a single
            duplicated or dropped token."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            delivered = 0
            nchunks = 0
            deadline = self._deadline(freq)
            try:
                while True:
                    toks = list(freq.tokens)
                    if len(toks) > delivered:
                        if delivered == 0:
                            observe("ingress.ttft_seconds",
                                    time.monotonic() - t0)
                        chunk = {"tokens": [int(t) for t in toks[delivered:]],
                                 "start": delivered}
                        delivered = len(toks)
                        nchunks += 1
                        self._write_chunk(
                            (json.dumps(chunk) + "\n").encode())
                        if chaos.ingress_disconnect_due(nchunks):
                            # deterministic client loss: force-drop the
                            # connection (shutdown, not just close — the
                            # wfile handle keeps the fd alive otherwise);
                            # the next write fails and the
                            # disconnect->cancel path takes over
                            try:
                                self.connection.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass
                            self.connection.close()
                        continue  # noqa: PTA103 (host-side ingress, never traced)
                    if freq.status in _TERMINAL or freq.fid in ingress.driver.lost:
                        break
                    if time.monotonic() > deadline:
                        ingress._cancel(freq.fid)
                        break
                    if self._client_gone():
                        raise OSError("client disconnected")
                    time.sleep(0.005)
                doc = self._finish_doc(freq)
                doc["done"] = True
                doc.pop("tokens", None)
                doc["new_tokens"] = delivered
                self._write_chunk((json.dumps(doc) + "\n").encode())
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
                counter_inc("ingress.responses")
                observe("ingress.request_seconds", time.monotonic() - t0)
                _runlog.emit("ingress", kind="response", id=freq.fid,
                             status=freq.status, http=200, stream=True,
                             new_tokens=delivered, chunks=nchunks,
                             seconds=time.monotonic() - t0,
                             trace=freq.trace_id)
            except (OSError, ValueError):
                # the client went away mid-stream: free the decode slot
                counter_inc("ingress.disconnect_cancels")
                _runlog.emit("ingress", kind="disconnect", id=freq.fid,
                             delivered=delivered, trace=freq.trace_id)
                if freq.status not in _TERMINAL:
                    ingress._cancel(freq.fid)
                self.close_connection = True

    return Handler
