"""Continuous (in-flight) batching scheduler over a :class:`DecodeEngine`.

Requests arrive at any time and are admitted into free batch slots
mid-stream: a new request's bucketed prefill runs while other slots keep
decoding, and every decode dispatch advances ALL occupied slots one token
(per-slot position indices, slot-masked sampling). No request waits for a
batch to drain — the vLLM/Orca serving discipline on top of the two
compiled programs.

Telemetry rides the PR-4 spine: every request emits ``request`` run-log
events (``submitted`` → ``admitted`` → ``finished``) with queue/prefill/
decode timings, the ``serving.*`` counters/gauges/histograms feed the
metrics registry, and ``python -m paddle_tpu.observability report`` renders
a serving section (request rate, queue depth, prefill/decode split,
p50/p99 latency) from the event stream.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Request", "ContinuousBatchingScheduler"]


class Request:
    """One in-flight generation request and its lifecycle timestamps."""

    def __init__(self, rid: int, prompt: np.ndarray, max_new_tokens: int,
                 eos_token_id: Optional[int], seed: int):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.seed = int(seed)
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        self.bucket: Optional[int] = None
        self.submitted_ts = time.perf_counter()
        self.admitted_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.finished_ts: Optional[float] = None

    # -- derived timings (None until the request reaches that phase) -------
    @property
    def queue_seconds(self):
        return None if self.admitted_ts is None else self.admitted_ts - self.submitted_ts

    @property
    def ttft_seconds(self):
        return None if self.first_token_ts is None else self.first_token_ts - self.submitted_ts

    @property
    def prefill_seconds(self):
        if self.admitted_ts is None or self.first_token_ts is None:
            return None
        return self.first_token_ts - self.admitted_ts

    @property
    def decode_seconds(self):
        if self.finished_ts is None or self.first_token_ts is None:
            return None
        return self.finished_ts - self.first_token_ts

    @property
    def total_seconds(self):
        return None if self.finished_ts is None else self.finished_ts - self.submitted_ts

    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens, the served completion."""
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])


class ContinuousBatchingScheduler:
    """Admit-into-free-slots scheduler: FIFO queue in front of the engine's
    batch slots. Drive it with :meth:`step` (one admission sweep + one
    decode dispatch) or :meth:`run` (until drained)."""

    def __init__(self, engine):
        self.engine = engine
        self.queue: deque = deque()
        self.running: Dict[int, Request] = {}  # slot -> request
        self.finished: Dict[int, Request] = {}  # rid -> request
        self._next_rid = 0

    # ----------------------------------------------------------- lifecycle
    def submit(self, prompt, max_new_tokens: int = 16, eos_token_id: Optional[int] = None,
               seed: int = 0) -> int:
        """Enqueue one prompt; returns the request id. Validation happens
        here (not at admission) so a bad request fails its caller, not the
        serving loop."""
        from ..observability import runlog as _runlog
        from ..observability.metrics import counter_inc, gauge_set

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(prompt.shape[0])
        if n + int(max_new_tokens) > self.engine.max_seq_len:
            raise ValueError(f"prompt {n} + max_new_tokens {max_new_tokens} exceeds "
                             f"engine max_seq_len {self.engine.max_seq_len}")
        self.engine.bucket_for(n)  # raises if no bucket fits
        r = Request(self._next_rid, prompt, max_new_tokens, eos_token_id, seed)
        self._next_rid += 1
        self.queue.append(r)
        counter_inc("serving.requests_submitted")
        gauge_set("serving.queue_depth", len(self.queue))
        _runlog.emit("request", id=r.rid, status="submitted", component="serving",
                     prompt_tokens=n, max_new_tokens=int(max_new_tokens),
                     queue_depth=len(self.queue))
        return r.rid

    def _admit(self) -> None:
        from ..observability import runlog as _runlog
        from ..observability.metrics import counter_inc, gauge_set, observe

        free = self.engine.free_slots()
        while self.queue and free:
            r = self.queue.popleft()
            slot = free.pop(0)
            r.slot = slot
            r.bucket = self.engine.bucket_for(len(r.prompt))
            r.admitted_ts = time.perf_counter()
            tok, more = self.engine.prefill(
                r.prompt, slot, max_new_tokens=r.max_new_tokens,
                eos_token_id=r.eos_token_id, seed=r.seed)
            r.first_token_ts = time.perf_counter()
            r.tokens.append(tok)
            counter_inc("serving.requests_admitted")
            observe("serving.ttft_seconds", r.ttft_seconds)
            observe("serving.queue_seconds", r.queue_seconds)
            gauge_set("serving.queue_depth", len(self.queue))
            gauge_set("serving.active_slots", len(self.running) + 1)
            _runlog.emit("request", id=r.rid, status="admitted", component="serving",
                         slot=slot, bucket=r.bucket, queue_depth=len(self.queue),
                         queue_seconds=r.queue_seconds, seconds=r.prefill_seconds)
            if more:
                self.running[slot] = r
            else:
                self._finish(r)

    def _finish(self, r: Request) -> None:
        from ..observability import runlog as _runlog
        from ..observability.metrics import counter_inc, gauge_set, observe

        r.finished_ts = time.perf_counter()
        self.engine.free_slot(r.slot)
        self.running.pop(r.slot, None)
        self.finished[r.rid] = r
        counter_inc("serving.requests_completed")
        counter_inc("serving.tokens_generated", len(r.tokens))
        observe("serving.latency_seconds", r.total_seconds)
        gauge_set("serving.active_slots", len(self.running))
        _runlog.emit("request", id=r.rid, status="finished", component="serving",
                     prompt_tokens=len(r.prompt), new_tokens=len(r.tokens),
                     queue_seconds=r.queue_seconds, prefill_seconds=r.prefill_seconds,
                     decode_seconds=r.decode_seconds, total_seconds=r.total_seconds,
                     ttft_seconds=r.ttft_seconds)

    def step(self) -> List[Request]:
        """One scheduler tick: admit queued requests into free slots
        (bucketed prefill each), then advance every occupied slot one token
        in a single decode dispatch. Returns requests finished this tick."""
        before = set(self.finished)
        self._admit()
        if self.running:
            toks, emitted, active = self.engine.decode_step()
            for slot, r in list(self.running.items()):
                if emitted[slot]:
                    r.tokens.append(int(toks[slot]))
                if not active[slot]:
                    self._finish(r)
        return [self.finished[rid] for rid in self.finished if rid not in before]

    def run(self, max_steps: Optional[int] = None) -> Dict[int, Request]:
        """Drive :meth:`step` until queue and slots drain (or ``max_steps``
        ticks); returns ``{rid: Request}`` for everything finished."""
        steps = 0
        while self.queue or self.running:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return dict(self.finished)
