"""Continuous (in-flight) batching scheduler over a :class:`DecodeEngine`.

Requests arrive at any time and are admitted into free batch slots
mid-stream: a new request's prefill runs while other slots keep decoding,
and every decode dispatch advances ALL occupied slots (per-slot position
indices, slot-masked sampling). No request waits for a batch to drain —
the vLLM/Orca serving discipline on top of a fixed compiled-program family.

Round 2 of the serving hot path rides the engine's three throughput knobs:

- **chunked prefill** (``prefill_chunk``): an admission is a sequence of
  fixed-size chunk dispatches driven one per tick, INTERLEAVED with decode
  — a 2k-token prompt no longer stalls every in-flight request for its
  whole prefill. The time prefill dispatches spend while other slots hold
  active decodes is the *stall*: tracked per request, observed in the
  ``serving.prefill_stall_seconds`` histogram, and reported as p50/p99 by
  ``observability report``.
- **fused decode** (``fuse=D``): one decode dispatch returns a ``[D, B]``
  token stack; the scheduler drains it in order, appending only tokens
  whose slot really emitted (finished slots self-deactivate in-graph).
- **prefix reuse** (``prefix_cache_mb``): admissions that hit the
  prompt-prefix KV cache skip the matched chunks entirely —
  ``admitted`` events carry ``prefix_tokens`` for hit-rate reporting.

Requests carry optional **deadlines** (``submit(deadline_s=...)``) and can
be **cancelled** mid-flight (:meth:`ContinuousBatchingScheduler.cancel`):
an expired or cancelled request frees its batch slot immediately — even
mid-decode — instead of holding it to drain, and lands in ``.cancelled``
with status ``deadline_exceeded``/``cancelled``. The serving fleet builds
its graceful degradation on both.

Telemetry rides the PR-4 spine: every request emits ``request`` run-log
events (``submitted`` → ``admitted`` → ``finished``, or ``cancelled``/
``deadline_exceeded``) with queue/prefill/
decode/stall timings, the ``serving.*`` counters/gauges/histograms feed
the metrics registry, and ``python -m paddle_tpu.observability report``
renders a serving section (request rate, queue depth, latency/TTFT
percentiles, prefix-hit rate, fused depth, stall percentiles) from the
event stream.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Request", "ContinuousBatchingScheduler"]


class Request:
    """One in-flight generation request and its lifecycle timestamps.

    ``status`` walks ``queued → prefilling → running → finished``, or ends
    at ``cancelled`` / ``deadline_exceeded`` when :meth:`ContinuousBatching\
Scheduler.cancel` (or the per-tick deadline sweep) reclaims it mid-flight.
    """

    def __init__(self, rid: int, prompt: np.ndarray, max_new_tokens: int,
                 eos_token_id: Optional[int], seed: int,
                 deadline_s: Optional[float] = None,
                 trace_id: Optional[str] = None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.seed = int(seed)
        self.deadline_s = float(deadline_s) if deadline_s is not None else None
        self.trace_id = trace_id      # one id across every process/replica
        self.status = "queued"
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        self.bucket: Optional[int] = None
        self.prefix_tokens = 0        # prompt rows supplied by the prefix cache
        self.prefill_chunks = 0       # model dispatches its prefill took
        self.stall_seconds = 0.0      # prefill time spent while decode waited
        self.submitted_ts = time.perf_counter()
        self.admitted_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.finished_ts: Optional[float] = None

    # -- derived timings (None until the request reaches that phase) -------
    @property
    def queue_seconds(self):
        return None if self.admitted_ts is None else self.admitted_ts - self.submitted_ts

    @property
    def ttft_seconds(self):
        return None if self.first_token_ts is None else self.first_token_ts - self.submitted_ts

    @property
    def prefill_seconds(self):
        if self.admitted_ts is None or self.first_token_ts is None:
            return None
        return self.first_token_ts - self.admitted_ts

    @property
    def decode_seconds(self):
        if self.finished_ts is None or self.first_token_ts is None:
            return None
        return self.finished_ts - self.first_token_ts

    @property
    def total_seconds(self):
        return None if self.finished_ts is None else self.finished_ts - self.submitted_ts

    def deadline_expired(self, now: Optional[float] = None) -> bool:
        """True when the request carries a deadline and it has passed."""
        if self.deadline_s is None:
            return False
        now = time.perf_counter() if now is None else now
        return now - self.submitted_ts > self.deadline_s

    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens, the served completion."""
        return np.concatenate([self.prompt, np.asarray(self.tokens, np.int32)])


class ContinuousBatchingScheduler:
    """Admit-into-free-slots scheduler: FIFO queue in front of the engine's
    batch slots. Drive it with :meth:`step` (one admission sweep + at most
    one prefill dispatch per in-flight admission + one decode dispatch) or
    :meth:`run` (until drained)."""

    def __init__(self, engine, keep_finished: int = 256):
        if keep_finished < 1:
            raise ValueError(f"keep_finished must be >= 1, got {keep_finished}")
        self.engine = engine
        self.keep_finished = int(keep_finished)
        self.queue: deque = deque()
        self.prefilling: Dict[int, Request] = {}  # slot -> mid-prefill request
        self._jobs: Dict[int, object] = {}        # slot -> engine _PrefillJob
        self.running: Dict[int, Request] = {}     # slot -> decoding request
        # terminal ledgers: delivered requests are GC'd past keep-last-k
        # (insertion order = completion order) so a long-lived serving loop
        # doesn't accrete per-request host state forever. In-flight requests
        # are never evicted — exactly-once delivery happens through the
        # step() return value before its tick's GC can touch an entry.
        self.finished: Dict[int, Request] = {}    # rid -> request
        self.cancelled: Dict[int, Request] = {}   # rid -> cancelled/expired
        self._next_rid = 0

    # ----------------------------------------------------------- lifecycle
    def submit(self, prompt, max_new_tokens: int = 16, eos_token_id: Optional[int] = None,
               seed: int = 0, deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> int:
        """Enqueue one prompt; returns the request id. Validation happens
        here (not at admission) so a bad request fails its caller, not the
        serving loop. ``deadline_s`` bounds the request's TOTAL time from
        submission: a request still queued, prefilling, or decoding when it
        expires is reclaimed on the next tick with status
        ``deadline_exceeded`` (its slot frees mid-decode — no drain wait).
        ``trace_id`` links this request to an existing distributed trace
        (the fleet passes its id down so submit→admit→prefill→decode→finish
        all correlate); without one a fresh id is allocated when tracing is
        enabled."""
        from ..observability import runlog as _runlog
        from ..observability import trace as _trace
        from ..observability.metrics import counter_inc, gauge_set

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(prompt.shape[0])
        if n + int(max_new_tokens) > self.engine.max_seq_len:
            raise ValueError(f"prompt {n} + max_new_tokens {max_new_tokens} exceeds "
                             f"engine max_seq_len {self.engine.max_seq_len}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.engine.bucket_for(n)  # raises if no bucket/chunk tiling fits
        if trace_id is None:
            trace_id = _trace.new_trace_id("serving")
        r = Request(self._next_rid, prompt, max_new_tokens, eos_token_id, seed,
                    deadline_s=deadline_s, trace_id=trace_id)
        self._next_rid += 1
        self.queue.append(r)
        counter_inc("serving.requests_submitted")
        gauge_set("serving.queue_depth", len(self.queue))
        _runlog.emit("request", id=r.rid, status="submitted", component="serving",
                     prompt_tokens=n, max_new_tokens=int(max_new_tokens),
                     queue_depth=len(self.queue), trace=r.trace_id)
        return r.rid

    def cancel(self, rid: int, status: str = "cancelled") -> bool:
        """Cancel one in-flight request wherever it is: still queued, mid-
        prefill, or mid-decode (its slot frees immediately — the next
        admission reuses it; write-before-attend cache hygiene makes the
        abandoned KV rows harmless). Emits a ``request`` run-log event with
        ``status`` (``cancelled``, or ``deadline_exceeded`` from the deadline
        sweep) and returns True; False when ``rid`` isn't in flight (already
        finished, cancelled, or never submitted)."""
        from ..observability import runlog as _runlog
        from ..observability.metrics import counter_inc, gauge_set

        r = None
        for q in self.queue:
            if q.rid == rid:
                r = q
                self.queue.remove(q)  # noqa: PTA104 (host-side serving loop, never traced)
                gauge_set("serving.queue_depth", len(self.queue))
                break
        if r is None:
            for slot, cand in list(self.prefilling.items()):  # noqa: PTA102 (host-side serving loop, never traced)
                if cand.rid == rid:
                    r = cand
                    del self.prefilling[slot], self._jobs[slot]
                    self.engine.free_slot(slot)
                    break
        if r is None:
            for slot, cand in list(self.running.items()):  # noqa: PTA102 (host-side serving loop, never traced)
                if cand.rid == rid:
                    r = cand
                    del self.running[slot]
                    self.engine.free_slot(slot)
                    break
        if r is None:
            return False
        r.status = status
        r.finished_ts = time.perf_counter()
        self.cancelled[rid] = r
        counter_inc("serving.deadline_exceeded" if status == "deadline_exceeded"
                    else "serving.requests_cancelled")
        gauge_set("serving.active_slots", len(self.running))
        _runlog.emit("request", id=rid, status=status, component="serving",
                     prompt_tokens=len(r.prompt), new_tokens=len(r.tokens),
                     seconds=r.finished_ts - r.submitted_ts,
                     deadline_s=r.deadline_s, trace=r.trace_id)
        return True

    def find(self, rid: int):
        """The in-flight :class:`Request` with id ``rid`` wherever it is
        (queued, prefilling, or decoding), else None — the live-progress
        view a streaming front end polls without touching slot state."""
        for q in self.queue:
            if q.rid == rid:
                return q  # noqa: PTA101 (host-side serving transport, never traced)
        for cand in self.prefilling.values():
            if cand.rid == rid:
                return cand  # noqa: PTA101 (host-side serving transport, never traced)
        for cand in self.running.values():
            if cand.rid == rid:
                return cand  # noqa: PTA101 (host-side serving transport, never traced)
        return None

    def _expire_deadlines(self) -> None:
        """Reclaim every in-flight request whose deadline has passed (one
        sweep per tick: queued, prefilling, and decoding alike)."""
        now = time.perf_counter()
        expired = [r.rid for r in list(self.queue) if r.deadline_expired(now)]
        expired += [r.rid for r in list(self.prefilling.values())
                    if r.deadline_expired(now)]
        expired += [r.rid for r in list(self.running.values())
                    if r.deadline_expired(now)]
        for rid in expired:
            self.cancel(rid, status="deadline_exceeded")

    def _admit(self) -> None:
        """Claim free slots for queued requests (prefix-cache inserts happen
        here — cheap copy dispatches, no model compute). The model prefill
        dispatches are driven chunk-at-a-time by :meth:`_prefill_tick`."""
        from ..observability.metrics import gauge_set

        free = self.engine.free_slots()
        while self.queue and free:
            r = self.queue.popleft()
            slot = free.pop(0)
            r.slot = slot  # noqa: PTA104 (host-side serving loop)
            r.bucket = self.engine.bucket_for(len(r.prompt))  # noqa: PTA104 (host-side serving loop)
            r.status = "prefilling"  # noqa: PTA104 (host-side serving loop, never traced)
            r.admitted_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop)
            job = self.engine.begin_prefill(
                r.prompt, slot, max_new_tokens=r.max_new_tokens,
                eos_token_id=r.eos_token_id, seed=r.seed)
            r.prefix_tokens = job.reused_tokens  # noqa: PTA104 (host-side serving loop)
            self.prefilling[slot] = r  # noqa: PTA104 (host-side serving loop)
            self._jobs[slot] = job  # noqa: PTA104 (host-side serving loop)
            gauge_set("serving.queue_depth", len(self.queue))

    def _prefill_tick(self) -> None:
        """ONE prefill dispatch per mid-prefill admission: in chunked mode a
        C-token chunk, in bucketed mode the whole padded prompt. Decode runs
        between ticks, so a long admission interleaves instead of stalling
        the stream; prefill time spent while decodes were waiting counts as
        stall."""
        from ..observability import runlog as _runlog
        from ..observability import trace as _trace
        from ..observability.metrics import counter_inc, gauge_set, observe

        for slot in list(self.prefilling):
            r = self.prefilling[slot]
            job = self._jobs[slot]
            decode_waiting = bool(self.running)
            t0 = time.perf_counter()
            done = self.engine.prefill_step(job)
            dt = time.perf_counter() - t0
            r.prefill_chunks += 1  # noqa: PTA104 (host-side serving loop)
            if r.trace_id is not None:
                _trace.span_event("serving.prefill_chunk", trace_id=r.trace_id,
                                  seconds=dt, id=r.rid, slot=slot,
                                  chunk=r.prefill_chunks, done=bool(done))
            if decode_waiting:
                r.stall_seconds += dt  # noqa: PTA104 (host-side serving loop)
                observe("serving.prefill_stall_seconds", dt)
            if not done:
                continue
            r.first_token_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop)
            r.tokens.append(job.first)  # noqa: PTA104 (host-side serving loop)
            del self.prefilling[slot], self._jobs[slot]
            counter_inc("serving.requests_admitted")
            observe("serving.ttft_seconds", r.ttft_seconds)
            observe("serving.queue_seconds", r.queue_seconds)
            gauge_set("serving.active_slots", len(self.running) + 1)
            _runlog.emit("request", id=r.rid, status="admitted", component="serving",
                         slot=slot, bucket=r.bucket, queue_depth=len(self.queue),
                         queue_seconds=r.queue_seconds, seconds=r.prefill_seconds,
                         prefix_tokens=r.prefix_tokens, chunks=r.prefill_chunks,
                         stall_seconds=r.stall_seconds, trace=r.trace_id)
            if job.more:
                r.status = "running"  # noqa: PTA104 (host-side serving loop, never traced)
                self.running[slot] = r  # noqa: PTA104 (host-side serving loop)
            else:
                self._finish(r)

    def _finish(self, r: Request) -> None:
        from ..observability import runlog as _runlog
        from ..observability.metrics import counter_inc, gauge_set, observe

        r.status = "finished"
        r.finished_ts = time.perf_counter()
        self.engine.free_slot(r.slot)
        self.running.pop(r.slot, None)
        self.finished[r.rid] = r
        counter_inc("serving.requests_completed")
        counter_inc("serving.tokens_generated", len(r.tokens))
        observe("serving.latency_seconds", r.total_seconds)
        gauge_set("serving.active_slots", len(self.running))
        extra = {}
        if getattr(self.engine, "spec_k", 0):
            stats = self.engine.spec_stats()
            extra["spec_k"] = stats["spec_k"]  # noqa: PTA104 (host-side serving loop)
            extra["spec_acceptance"] = stats["acceptance_rate"]  # noqa: PTA104 (host-side serving loop)
        _runlog.emit("request", id=r.rid, status="finished", component="serving",
                     prompt_tokens=len(r.prompt), new_tokens=len(r.tokens),
                     queue_seconds=r.queue_seconds, prefill_seconds=r.prefill_seconds,
                     decode_seconds=r.decode_seconds, total_seconds=r.total_seconds,
                     ttft_seconds=r.ttft_seconds, fuse=self.engine.fuse,
                     prefix_tokens=r.prefix_tokens, stall_seconds=r.stall_seconds,
                     kv_bytes_per_slot=getattr(
                         self.engine, "kv_bytes_per_slot", lambda: 0)(),
                     trace=r.trace_id, **extra)

    def step(self) -> List[Request]:
        """One scheduler tick: admit queued requests into free slots, run
        one prefill dispatch per mid-prefill admission, then advance every
        decoding slot in a single decode dispatch (a ``[D, B]`` token stack
        at fuse depth D, drained in order). Returns requests finished this
        tick."""
        before = set(self.finished)
        before_cancelled = set(self.cancelled)
        self._expire_deadlines()
        self._admit()
        self._prefill_tick()
        if self.running:
            traced = sorted({r.trace_id for r in self.running.values()
                             if r.trace_id is not None})
            t0 = time.perf_counter()
            toks, emitted, active = self.engine.decode_step()
            if traced:
                from ..observability import trace as _trace

                # one fused dispatch advances EVERY running slot: a single
                # span event fanned across the traces it served
                _trace.span_event("serving.decode", trace_id=None,
                                  seconds=time.perf_counter() - t0,
                                  traces=traced, slots=len(self.running))
            toks = np.atleast_2d(toks)
            emitted = np.atleast_2d(emitted)
            for d in range(toks.shape[0]):
                for slot, r in self.running.items():  # noqa: PTA102 (host-side serving loop)
                    if emitted[d, slot]:
                        r.tokens.append(int(toks[d, slot]))  # noqa: PTA104 (host-side serving loop)
            for slot, r in list(self.running.items()):  # noqa: PTA102 (host-side serving loop)
                if not active[slot]:
                    self._finish(r)
        done = [self.finished[rid] for rid in self.finished if rid not in before]
        fresh = ({rid for rid in self.finished if rid not in before}
                 | {rid for rid in self.cancelled if rid not in before_cancelled})
        self._gc_ledgers(protect=fresh)
        from ..observability import slo as _slo

        # judgment layer: cadence-gated host-side evaluate — a single flag
        # check per tick until FLAGS_slo (or an explicit install) arms it
        _slo.on_tick()
        return done

    def _gc_ledgers(self, protect=()) -> None:
        """Keep-last-k GC of the terminal ledgers: evict the OLDEST entries
        past ``keep_finished`` (dict insertion order is completion order).
        ``protect`` holds THIS tick's rids — never evicted, so the caller of
        :meth:`step` (the fleet's harvest) always sees them, even when a
        mass deadline expiry terminates more than k requests in one tick."""
        protect = set(protect)
        overflow = len(self.finished) - self.keep_finished
        for rid in [r for r in self.finished
                    if r not in protect][:max(0, overflow)]:
            del self.finished[rid]
        overflow = len(self.cancelled) - self.keep_finished
        for rid in [r for r in self.cancelled
                    if r not in protect][:max(0, overflow)]:
            del self.cancelled[rid]

    def run(self, max_steps: Optional[int] = None) -> Dict[int, Request]:
        """Drive :meth:`step` until queue and slots drain (or ``max_steps``
        ticks); returns ``{rid: Request}`` for everything finished during
        the run — accumulated across ticks, so completions the keep-last-k
        ledger GC has since evicted are still returned."""
        done: Dict[int, Request] = dict(self.finished)
        steps = 0
        while self.queue or self.prefilling or self.running:
            for r in self.step():
                done[r.rid] = r  # noqa: PTA104 (host-side serving loop)
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        done.update(self.finished)
        return done
