"""Request router for the serving fleet: prefix-cache affinity first,
load-aware placement second.

A fleet of engine replicas each keeps its own :class:`~.prefix_cache.\
PrefixCache` of chunk-aligned prompt-prefix KV segments. Two requests that
share a system prompt therefore decode fastest on the SAME replica — the
second skips the shared chunks entirely. The router exploits that without
asking the replicas anything: it remembers, per chunk-aligned prefix chain,
which replica last prefilled it, keyed on the **exact token ids of the whole
chain** — the same byte keys :class:`~.prefix_cache.PrefixCache` uses, so a
router hit is (modulo that replica's LRU eviction) a prefix-cache hit.

Placement discipline:

1. **affinity** — walk the prompt's chunk chain longest-first; the first
   chain some healthy replica is known to hold wins, UNLESS that replica is
   overloaded relative to the fleet (its load exceeds the least-loaded
   replica's by more than ``affinity_load_slack`` in-flight requests —
   reusing a few cached chunks never justifies queueing behind a long line);
2. **least load** — otherwise the healthy replica with the fewest in-flight
   requests (ties break on replica id for determinism).

The router is host-side bookkeeping only: no device memory, no dispatches.
``forget_replica`` drops a dead replica's chains so affinity can't route
into a corpse; the requeue path then re-registers chains on the survivors
as they re-prefill.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["Router"]


class Router:
    """Prefix-affinity + load-aware placement over fleet replica ids.

    ``chunk`` is the prefix-chain granularity (the engines'
    ``prefill_chunk``); None disables affinity entirely (bucketed engines
    keep no reusable prefix segments), leaving pure least-load placement.
    """

    def __init__(self, chunk: Optional[int] = None,
                 affinity_load_slack: int = 2):
        self.chunk = int(chunk) if chunk else None
        self.affinity_load_slack = int(affinity_load_slack)
        self._chains: Dict[bytes, int] = {}   # chain byte key -> replica id
        self.affinity_hits = 0
        self.load_placements = 0

    # ------------------------------------------------------------------ keys
    def _key(self, prompt: np.ndarray, k: int) -> bytes:
        """Chain key of the first ``k`` chunks — the PrefixCache byte-key
        discipline: exact token ids of the whole prefix, no hashing."""
        return np.ascontiguousarray(prompt[: k * self.chunk], np.int32).tobytes()

    # ------------------------------------------------------------- placement
    def place(self, prompt, loads: Dict[int, int]) -> Tuple[int, str]:
        """Pick a replica for ``prompt`` among ``loads`` (healthy replica id
        -> in-flight request count). Returns ``(replica_id, reason)`` with
        reason ``"affinity"`` or ``"load"``; raises when ``loads`` is empty
        (no healthy replica — the fleet's no-capacity fault)."""
        if not loads:
            raise RuntimeError("router: no healthy replicas to place on")
        floor = min(loads.values())
        if self.chunk is not None:
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            # longest-first: the deepest cached chain wins (most reuse);
            # cap at n-1 tokens — the last prompt token always re-runs
            for k in range(max(0, (int(prompt.shape[0]) - 1) // self.chunk), 0, -1):
                rid = self._chains.get(self._key(prompt, k))
                if rid is None or rid not in loads:
                    continue
                if loads[rid] - floor > self.affinity_load_slack:
                    break  # holder is drowning; cheaper to re-prefill elsewhere
                self.affinity_hits += 1  # noqa: PTA104 (host-side serving loop, never traced)
                return rid, "affinity"  # noqa: PTA101 (host-side serving loop, never traced)
        rid = min(loads, key=lambda r: (loads[r], r))
        self.load_placements += 1
        return rid, "load"

    # ---------------------------------------------------------- registration
    def register(self, prompt, replica_id: int) -> int:
        """Record that ``replica_id`` is prefilling ``prompt``: every
        chunk-aligned prefix chain of it now routes there (last writer wins —
        the newest prefill is the one whose cache entries are freshest).
        Returns the number of chains registered."""
        if self.chunk is None:
            return 0
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n_chains = max(0, (int(prompt.shape[0]) - 1) // self.chunk)
        for k in range(1, n_chains + 1):
            self._chains[self._key(prompt, k)] = int(replica_id)  # noqa: PTA104 (host-side serving loop, never traced)
        return n_chains

    def forget_replica(self, replica_id: int) -> int:
        """Drop every chain owned by ``replica_id`` (replica death: its KV
        cache is gone, affinity to it would be worse than useless). Returns
        the number of chains dropped."""
        dead = [k for k, rid in self._chains.items() if rid == int(replica_id)]
        for k in dead:
            del self._chains[k]
        return len(dead)

    def stats(self) -> dict:
        return {
            "chains": len(self._chains),
            "affinity_hits": self.affinity_hits,
            "load_placements": self.load_placements,
        }
