"""Persist AOT-compiled executables across process restarts.

A restarted engine pays the full prefill/decode compile family again before
it can serve its first token — the ROADMAP restart-latency leftover. When
``FLAGS_compile_cache_dir`` is set, every serving program the engine
compiles is also serialized (``jax.experimental.serialize_executable`` —
the raw PJRT executable plus its call trees) under
``<dir>/serving/<key>.aotc``, keyed on the (kind, argument avals, engine
fingerprint, jax version, backend) specialization. A fresh engine with the
same specialization loads the executable instead of recompiling: restart
``time_to_first_token`` drops to deserialize+dispatch cost
(bench_serve.py reports it as ``restart_ttft``).

The same store serves *training*: ``TrainStep`` and the static ``Executor``
round-trip their compiled step programs through ``<dir>/train_step/`` and
``<dir>/executor/`` (see ``observability.introspect.aot_compile``'s
``cache_scope``), keyed on the lowered program text — a warm restart (or an
elastic resume onto a mesh the planner already evaluated) skips straight to
dispatch, which is what cuts ``time_to_first_step``.

Everything here is best-effort: backends without executable serialization,
version drift, or a corrupt file all degrade to the normal compile path —
persistence must never break dispatch. Writes are atomic
(temp + ``os.replace``) so concurrent engines can share a directory.
"""
from __future__ import annotations

import hashlib
import os
import pickle
from pathlib import Path
from typing import Any, Optional

__all__ = ["cache_dir", "make_key", "load", "store"]

_FORMAT = "aotc-v1"


def cache_dir(scope: str = "serving") -> Optional[Path]:
    """The executable cache directory for ``scope`` (serving / train_step /
    executor / ...), or None when ``FLAGS_compile_cache_dir`` is unset."""
    from ..framework.flags import flag

    d = flag("FLAGS_compile_cache_dir")
    if not d:
        return None
    return Path(str(d)) / scope


def make_key(kind: str, sig: Any, fingerprint: str) -> str:
    """Stable content key for one compiled specialization: the program kind
    (prefill / decode / decode_xD / spec_decode / chunk / ...), the argument
    avals, the engine's config fingerprint (model dims, sampling config,
    dtypes, kv-cache dtype, and the speculative draft config + spec_k — the
    host scalars baked into the trace), and the jax version + backend the
    executable was built for."""
    import jax

    payload = repr((_FORMAT, kind, sig, fingerprint, jax.__version__,
                    jax.default_backend()))
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def load(key: str, scope: str = "serving"):
    """Deserialize + load the executable stored under ``key``; None on any
    miss or failure (caller compiles normally)."""
    d = cache_dir(scope)
    if d is None:
        return None
    path = d / f"{key}.aotc"
    if not path.exists():
        return None
    try:
        from jax.experimental.serialize_executable import deserialize_and_load

        payload, in_tree, out_tree = pickle.loads(path.read_bytes())
        return deserialize_and_load(payload, in_tree, out_tree)
    except Exception:
        return None


def store(key: str, compiled, scope: str = "serving") -> bool:
    """Serialize ``compiled`` (an XLA ``Compiled`` from ``lower().compile()``)
    under ``key``. False (and no file) when the backend can't serialize
    executables or the directory is unwritable."""
    d = cache_dir(scope)
    if d is None:
        return False
    tmp = None
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        d.mkdir(parents=True, exist_ok=True)
        tmp = d / f".{key}.{os.getpid()}.tmp"
        tmp.write_bytes(pickle.dumps((payload, in_tree, out_tree)))
        os.replace(tmp, d / f"{key}.aotc")
        return True
    except Exception:
        if tmp is not None:
            try:
                tmp.unlink()
            except OSError:
                pass
        return False
