"""Cross-process serving fleet: subprocess replicas that survive kill -9.

PR 11's :class:`~.fleet.ServingFleet` runs its replicas in-process, so a
"replica death" is a caught Python exception — a segfault, an OOM-kill, or
a wedged XLA dispatch in any replica still takes the whole fleet down.
Here each replica is a **subprocess** hosting its own DecodeEngine +
continuous-batching scheduler, speaking the compact store-RPC of
``rpc.py`` (submit / tick / token-chunk / heartbeat / drain) over the
TCPStore, and booting warm from the shared ``FLAGS_compile_cache_dir``
AOT executable cache at ``infer.compiles == 0``.

The router/ledger/failure semantics carry over from ``fleet.py``
unchanged — only the transport is new:

- **supervision** — the parent detects death two ways: process liveness
  (``Popen.poll`` / ``kill(pid, 0)`` — catches SIGKILL and segfaults the
  child never got to report) and a stale-beat sweep (the child publishes
  a monotonic beat counter from a daemon thread; a child that stops
  beating without exiting — ``FLAGS_chaos_replica_hang_ms`` — is a zombie
  only this sweep can catch). Either way: chains forgotten, in-flight
  requests requeued from the PARENT's ledger (the dead child's
  bookkeeping is treated as lost) with original prompt + seed + remaining
  deadline, so completions stay **bitwise-identical to an unkilled run,
  delivered exactly once** — now proven against a real ``kill -9``.
- **per-token streaming** — ``submit(stream=True)`` returns a
  :class:`TokenStream` that yields in-order token chunks as decode
  progresses. The exactly-once ledger extends to chunk sequence numbers:
  ``FleetRequest.tokens`` is a monotonic, append-only delivery ledger;
  an arriving chunk ``(start, tokens)`` contributes only the suffix past
  what was already delivered, so a post-requeue replay (which re-streams
  from position 0, bitwise-identically) resumes the stream without
  duplicating or reordering a single delivered token.
- **exactly-once across death** — when a replica dies the parent drains
  its out-channel one final time before requeueing: a request the child
  *finished* before dying is delivered from that harvest (never
  replayed); one it didn't is replayed bitwise on a survivor. The ledger
  writes ``tokens`` to completion exactly once either way.
- **observability across the process boundary** — the RPC envelope
  carries the fleet ``trace_id``; the child attaches it to its scheduler
  submission so spans from both processes join one trace; child run logs
  land in the same ``FLAGS_run_log_dir`` (``observability report
  --merge`` renders parent + replica lanes with requeue edges intact);
  a child crash dumps a ``flightrec-<pid>.json`` from the PARENT side
  naming the dead rid and its in-flight fids.

Multi-host: ``python -m paddle_tpu.distributed.launch --serve spec.json``
boots replicas from the launcher with store-registered membership;
:meth:`ProcServingFleet.attach` adopts them as the serving front.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set

import numpy as np

from ..analysis import sanitizer as _sanitizer
from ..framework.flags import flag
from ..observability import flightrec as _flightrec
from ..observability import runlog as _runlog
from ..observability import slo as _slo
from ..observability import trace as _trace
from ..observability.metrics import counter_inc, gauge_set, observe
from ..testing import chaos
from .fleet import (FleetDrainedError, FleetOverloadError, FleetRequest,
                    retry_after_estimate)
from .router import Router
from .rpc import (Heartbeat, SocketChannel, SocketListener, channel_prefix,
                  connect_socket, sock_key)

__all__ = ["ProcServingFleet", "ProcReplica", "TokenStream", "replica_main"]

SPEC_ENV = "PADDLE_PROCFLEET_SPEC"

# flag VALUES (not just env) forwarded into every replica subprocess: tests
# and drivers set these via set_flags, which a child env would never see
_FLAG_FORWARD = (
    "FLAGS_compile_cache_dir", "FLAGS_run_log_dir", "FLAGS_monitor",
    "FLAGS_trace", "FLAGS_flightrec_events", "FLAGS_chaos",
    "FLAGS_chaos_replica_hang_ms", "FLAGS_chaos_replica_slow_ms",
    "FLAGS_chaos_socket_drop_at", "FLAGS_chaos_net_delay_ms",
    "FLAGS_sanitize", "FLAGS_sanitize_strict", "FLAGS_sanitize_max_recompiles",
)

_TERMINAL = ("finished", "cancelled", "deadline_exceeded")
_ns_counter = [0]

# child entry via -c (not -m): `-m paddle_tpu.inference.procfleet` would
# import the inference package first and re-execute this module as
# __main__ on top of the already-imported copy (runpy warns)
CHILD_CMD = [sys.executable, "-u", "-c",
             "import sys; from paddle_tpu.inference.procfleet import "
             "replica_main; sys.exit(replica_main())"]


def current_jax_config() -> dict:
    """The parent's bitwise-relevant jax.config knobs, forwarded through
    the spec so a child reproduces the parent's numerics even when the
    parent configured them programmatically (a test conftest pinning
    matmul precision, a driver forcing the cpu platform) rather than via
    inheritable env vars."""
    import jax

    out = {}
    for opt in ("jax_platforms", "jax_default_matmul_precision"):
        v = getattr(jax.config, opt, None)
        if v:
            out[opt] = v  # noqa: PTA104 (host-side, never traced)
    return out


def child_env(extra_env: dict) -> dict:
    """The subprocess environment: current env + spec/rank overrides +
    the forwarded flag VALUES (set_flags changes never reach a plain env
    copy) + a sys.path guarantee that the child can import paddle_tpu."""
    env = dict(os.environ)
    env.update(extra_env)
    for name in _FLAG_FORWARD:
        v = flag(name)
        env[name] = ("1" if v else "0") if isinstance(v, bool) else str(v)  # noqa: PTA104 (host-side, never traced)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    return env


# =====================================================================
# child side: one replica subprocess
# =====================================================================

class _Beater(threading.Thread):
    """Daemon thread publishing the replica heartbeat. A thread (not the
    serving loop) so a long compile doesn't read as death — readiness is
    not liveness — while a SIGKILL or segfault silences it instantly.
    ``FLAGS_chaos_replica_hang_ms`` wedges it via ``hang_until``."""

    def __init__(self, hb: Heartbeat, interval: float, state: dict):
        super().__init__(daemon=True, name="procfleet-beat")
        self.hb = hb
        self.interval = interval
        self.state = state          # mutated by the serving loop
        self.hang_until = 0.0
        self.stop_ev = threading.Event()

    def beat_once(self) -> None:
        from ..observability.metrics import counters

        c = counters("infer.")
        try:
            self.hb.beat(pid=os.getpid(), host=socket.gethostname(),
                         ready=self.state.get("ready", False),
                         ticks=self.state.get("ticks", 0),
                         load=self.state.get("load", 0),
                         compiles=int(c.get("infer.compiles", 0)),
                         aot_cache_hits=int(c.get("infer.aot_cache_hits", 0)))
        except OSError:
            pass  # store hiccup: the next beat retries; RetryingStore backs off

    def run(self) -> None:
        self.beat_once()
        while not self.stop_ev.wait(self.interval):
            if time.monotonic() < self.hang_until:
                continue  # chaos hang: alive but silent
            self.beat_once()


def replica_main(spec: Optional[dict] = None) -> int:
    """The replica subprocess entry (``python -m
    paddle_tpu.inference.procfleet``): build model + engine + scheduler
    from the ``PADDLE_PROCFLEET_SPEC`` JSON, register membership, then
    loop — drain submits, tick the scheduler, stream token chunks and
    tick results back, beat from the side thread — until a drain message
    or SIGTERM/SIGKILL ends it."""
    if spec is None:
        spec = json.loads(os.environ[SPEC_ENV])
    rid = int(spec["rid"])
    ns = spec["ns"]
    host, port = spec["endpoint"].rsplit(":", 1)

    import jax

    for opt, val in (spec.get("jax_config") or {}).items():  # noqa: PTA102 (host-side, never traced)
        try:
            jax.config.update(opt, val)  # noqa: PTA104 — before any backend initializes
        except (AttributeError, ValueError):
            pass

    from ..distributed.resilience import RetryingStore
    from ..distributed.store import TCPStore
    from ..framework import random as _random
    from ..models.gpt import GPTConfig, GPTForPretraining
    from .engine import DecodeEngine
    from .scheduler import ContinuousBatchingScheduler

    store = RetryingStore(TCPStore(
        host, int(port), is_master=False, world_size=1,
        timeout=float(spec.get("store_timeout", 60.0))))
    state = {"ready": False, "ticks": 0, "load": 0}
    beater = _Beater(Heartbeat(store, ns, rid), float(spec.get("beat_interval", 0.05)), state)
    beater.start()

    # deterministic rebuild: same seed -> bitwise-identical weights to the
    # parent's reference model; same engine kwargs -> same fingerprint ->
    # the shared AOT cache serves this whole process's program family
    mspec = spec.get("model", {})
    _random.seed(int(mspec.get("seed", 0)))
    model = GPTForPretraining(GPTConfig(**mspec.get("config", {})))
    model.eval()
    engine = DecodeEngine(model, **spec.get("engine_kwargs", {}))
    sched = ContinuousBatchingScheduler(engine)

    # hot-path transport: both logical channels share one fast-path socket
    # (installed into conn_box when the parent dials in); until then — and
    # after any socket death — the same channels ride the store
    conn_box: List[Any] = [None]
    in_ch = SocketChannel(store, channel_prefix(ns, rid, "in"), "in",
                          conn_box, rid=rid)
    out_ch = SocketChannel(store, channel_prefix(ns, rid, "out"), "out",
                           conn_box, rid=rid)
    listener = None
    if spec.get("socket", True):
        adv = ("127.0.0.1" if host in ("127.0.0.1", "localhost", "0.0.0.0")
               else socket.gethostname())
        listener = SocketListener(advertise_host=adv)
        # the endpoint must be advertised BEFORE the ready beat: the parent
        # dials exactly once, when it first observes ready
        store.set(sock_key(ns, rid), listener.address)
    store.add(f"procfleet/{ns}/members_n", 1)  # launcher-mode membership
    state["ready"] = True
    beater.beat_once()

    # clock alignment for the merged timeline: offset vs the parent (rank 0)
    try:
        raw = store.get(f"{_trace.EPOCH_KEY_PREFIX}/0/epoch", timeout=5.0)
        own = time.time()
        _runlog.emit("clock_sync", rank=rid + 1, epoch=own,
                     offset=own - float(raw if isinstance(raw, str) else raw.decode()),
                     world_size=0)
    except (TimeoutError, OSError, ValueError):
        pass

    local: Dict[int, Any] = {}   # fid -> scheduler Request
    sent: Dict[int, int] = {}    # fid -> tokens already chunk-streamed
    idle_sleep = float(spec.get("idle_sleep", 0.005))
    while True:
        if listener is not None and conn_box[0] is None:
            conn = listener.try_accept()
            if conn is not None:
                conn_box[0] = conn  # noqa: PTA104 (host-side, never traced)
        for m in in_ch.recv():
            kind = m["kind"]
            if kind == "submit":
                sched.submit(np.asarray(m["prompt"], np.int32),
                             max_new_tokens=m["max_new_tokens"],
                             eos_token_id=m.get("eos_token_id"),
                             seed=m.get("seed", 0),
                             deadline_s=m.get("deadline_s"),
                             trace_id=m.get("trace"))
                req = sched.queue[-1]  # submit validated + appended it
                local[m["fid"]] = req  # noqa: PTA104 (host-side, never traced)
                sent[m["fid"]] = 0  # noqa: PTA104 (host-side, never traced)
            elif kind == "cancel":
                req = local.get(m["fid"])
                if req is not None:
                    sched.cancel(req.rid, status=m.get("status", "cancelled"))
            elif kind == "drain":
                # flip NotReady FIRST: an attach() racing this drain sees a
                # non-ready beat and times out with a structured error
                # instead of adopting a corpse
                state["ready"] = False  # noqa: PTA104 (host-side, never traced)
                out_ch.send("bye", ticks=state["ticks"])
                beater.beat_once()
                beater.stop_ev.set()
                if listener is not None:
                    listener.close()
                if conn_box[0] is not None:
                    conn_box[0].close()
                store.close()
                return 0  # noqa: PTA101 (host-side, never traced)
        busy = bool(sched.queue or sched.prefilling or sched.running)
        if busy:
            sched.step()
            state["ticks"] += 1  # noqa: PTA104 (host-side, never traced)
        finished_fids: List[int] = []
        for fid, req in list(local.items()):  # noqa: PTA102 (host-side serving loop, never traced)
            if len(req.tokens) > sent[fid]:
                out_ch.send("chunk", fid=fid, start=sent[fid],
                            tokens=[int(t) for t in req.tokens[sent[fid]:]],
                            trace=req.trace_id)
                sent[fid] = len(req.tokens)  # noqa: PTA104 (host-side, never traced)
            if req.status in _TERMINAL:
                out_ch.send("finished", fid=fid, status=req.status,
                            tokens=[int(t) for t in req.tokens],
                            ttft_s=req.ttft_seconds, total_s=req.total_seconds,
                            trace=req.trace_id)
                finished_fids.append(fid)  # noqa: PTA104 (host-side serving loop, never traced)
                del local[fid], sent[fid]
        if not busy and not finished_fids:
            # idle — but only after the report sweep: a cancel() that just
            # emptied the scheduler still owes its terminal message
            time.sleep(idle_sleep)
            continue
        state["load"] = len(sched.queue) + len(sched.prefilling) + len(sched.running)  # noqa: PTA104 (host-side, never traced)
        out_ch.send("tick", tick=state["ticks"], finished=finished_fids,
                    load=state["load"])
        hang_ms = chaos.replica_hang_due_ms(rid)
        if hang_ms > 0:
            # the zombie shape: the process stays alive, the beat goes dark,
            # and the serving loop wedges — only the parent's stale-beat
            # sweep can tell; it SIGKILLs us mid-sleep
            beater.hang_until = time.monotonic() + hang_ms / 1e3  # noqa: PTA104 (host-side, never traced)
            time.sleep(hang_ms / 1e3)


# =====================================================================
# parent side: supervisor + ledger + streaming front
# =====================================================================

class ProcReplica:
    """Parent-side handle to one replica subprocess: the Popen (None when
    adopted via :meth:`ProcServingFleet.attach`), its RPC channels, and
    the liveness view (beat-counter motion on the PARENT's monotonic
    clock — wall-clock skew cannot fake a death)."""

    def __init__(self, rid: int, proc: Optional[subprocess.Popen],
                 in_ch: SocketChannel, out_ch: SocketChannel, hb: Heartbeat,
                 conn_box: Optional[list] = None):
        self.rid = int(rid)
        self.proc = proc
        self.in_ch = in_ch
        self.out_ch = out_ch
        self.hb = hb
        self.conn_box = conn_box if conn_box is not None else [None]
        self.sock_tried = False  # the parent dials the fast path exactly once
        self.alive = True
        self.draining = False
        self.death_reason: Optional[str] = None
        self.ticks = 0                   # tick messages harvested
        self.completed = 0
        self.assigned: Set[int] = set()  # fids in flight (parent view)
        self.reported_load = 0
        self.ready = False
        self.beat_n = -1
        self.last_beat = time.monotonic()
        self.pid: Optional[int] = proc.pid if proc is not None else None
        self.host: Optional[str] = None
        self.counters: Dict[str, int] = {}

    def load(self) -> int:
        """In-flight requests from the parent ledger's view (the child's
        own queue depth arrives asynchronously via tick/beat messages)."""
        return len(self.assigned)

    def process_alive(self) -> bool:
        """Liveness of the OS process — catches SIGKILL/segfault before
        any beat goes stale. Adopted cross-host replicas fall back to the
        stale-beat sweep (a remote pid can't be probed)."""
        if self.proc is not None:
            return self.proc.poll() is None
        if self.pid is None or self.host != socket.gethostname():
            return True
        try:
            os.kill(self.pid, 0)
            return True
        except ProcessLookupError:
            return False
        except OSError:
            return True

    def sigkill(self) -> None:
        if self.proc is not None:
            self.proc.kill()
        elif self.pid is not None and self.host == socket.gethostname():
            try:
                os.kill(self.pid, signal.SIGKILL)
            except OSError:
                pass


class TokenStream:
    """The ``submit(stream=True)`` handle: iterating yields in-order token
    chunks (lists of ints) as decode progresses, driving the fleet loop
    between arrivals. Exactly-once across a requeue falls out of the
    ledger: chunks are cut from the monotonic ``FleetRequest.tokens``
    append log, so a mid-stream replica death replays upstream but never
    re-yields, drops, or reorders a delivered token."""

    def __init__(self, fleet: "ProcServingFleet", fid: int):
        self.fleet = fleet
        self.fid = fid
        self.delivered = 0  # tokens yielded so far == the chunk cursor
        # hold the FleetRequest OBJECT, not a ledger lookup: the object is
        # stable across requeues, and the keep-last-k ledger GC must never
        # be able to break a live stream
        self._freq = fleet.requests[fid]

    @property
    def request(self) -> FleetRequest:
        return self._freq

    def __iter__(self):
        while True:
            freq = self.request
            if len(freq.tokens) > self.delivered:
                chunk = [int(t) for t in freq.tokens[self.delivered:]]
                self.delivered += len(chunk)  # noqa: PTA104 (host-side, never traced)
                yield chunk
                continue
            if freq.status in _TERMINAL:
                return  # noqa: PTA101 (host-side, never traced)
            self.fleet.step()
            time.sleep(self.fleet.poll_s)


class ProcServingFleet:
    """N replica subprocesses behind the prefix-affinity router, with the
    in-process fleet's kill-safe drain/requeue, deadlines, and shedding —
    but real process isolation: a SIGKILLed, segfaulted, or wedged child
    takes only itself down.

    ``model_config`` (a GPTConfig or its kwargs dict) + ``model_seed`` let
    each child rebuild bitwise-identical weights; every ``engine_kwargs``
    knob is shared so one warm ``FLAGS_compile_cache_dir`` serves the whole
    fleet's program family and children boot at ``infer.compiles == 0``
    (their beats report the per-process counters — see
    :meth:`child_counters`).

    ``heartbeat_timeout`` (seconds) is the stale-beat window: a replica
    whose beat counter hasn't moved for that long is declared dead even if
    its process is still up (the hang case). Process exit is always death,
    detected on the next :meth:`step`. ``max_queue_depth`` bounds TOTAL
    in-flight requests across alive replicas (the parent cannot see a
    child's internal queue synchronously, so admission counts its own
    ledger); past it :meth:`submit` sheds with
    :class:`~.fleet.FleetOverloadError`.
    """

    def __init__(self, model_config=None, *, model_seed: int = 0,
                 replicas: int = 2, max_queue_depth: int = 64,
                 heartbeat_timeout: float = 5.0, endpoint: Optional[str] = None,
                 ns: Optional[str] = None, boot_timeout: float = 120.0,
                 beat_interval: float = 0.05, poll_s: float = 0.002,
                 affinity_load_slack: int = 2, spawn: bool = True,
                 keep_finished: int = 256, use_sockets: bool = True,
                 **engine_kwargs):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if keep_finished < 1:
            raise ValueError(f"keep_finished must be >= 1, got {keep_finished}")
        if model_config is None:
            self.model_config: Dict[str, Any] = {}  # noqa: PTA104 (host-side, never traced)
        elif isinstance(model_config, dict):
            self.model_config = dict(model_config)  # noqa: PTA104 (host-side, never traced)
        else:
            self.model_config = dict(vars(model_config))  # noqa: PTA104 (host-side, never traced)
        self.model_seed = int(model_seed)
        self.engine_kwargs = dict(engine_kwargs)
        # the replica spec crosses a process boundary as JSON: a draft model
        # config must travel as its constructor kwargs (each replica rebuilds
        # it bitwise from draft_seed); a live model object cannot
        draft = self.engine_kwargs.get("draft")
        if draft is not None:
            if hasattr(draft, "to_dict"):
                self.engine_kwargs["draft"] = draft.to_dict()  # noqa: PTA104 (host-side serving loop)
            elif not isinstance(draft, dict):
                raise TypeError(
                    "ProcServingFleet needs draft= as a GPTConfig or a dict of "
                    "GPTConfig kwargs (replica subprocesses rebuild it from "
                    "draft_seed); a model instance does not serialize")
        self.max_queue_depth = int(max_queue_depth)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.boot_timeout = float(boot_timeout)
        self.beat_interval = float(beat_interval)
        self.poll_s = float(poll_s)
        # socket fast path: children advertise a framed-TCP endpoint the
        # parent dials once ready; False pins everything to the store
        # transport (the bench's socket_vs_store_overhead_pct baseline arm)
        self.use_sockets = bool(use_sockets)
        self.router = Router(chunk=engine_kwargs.get("prefill_chunk"),
                             affinity_load_slack=affinity_load_slack)

        from ..distributed.resilience import RetryingStore
        from ..distributed.store import TCPStore

        self._own_store = endpoint is None
        if self._own_store:
            raw_store = TCPStore("127.0.0.1", 0, is_master=True,
                                 world_size=1, timeout=60.0)
            endpoint = f"127.0.0.1:{raw_store.port}"
        else:
            host, port = endpoint.rsplit(":", 1)
            # a dead endpoint must fail within the caller's boot budget,
            # not the store client's own (longer) default connect window
            raw_store = TCPStore(host, int(port), is_master=False,
                                 world_size=1,
                                 timeout=min(60.0, self.boot_timeout))
        self._raw_store = raw_store
        self._store = RetryingStore(raw_store)
        self.endpoint = endpoint
        if ns is None:
            _ns_counter[0] += 1  # noqa: PTA104 (host-side, never traced)
            ns = f"{os.getpid():x}-{_ns_counter[0]}"
        self.ns = ns

        self.keep_finished = int(keep_finished)
        self.replicas: Dict[int, ProcReplica] = {}
        # terminal entries are GC'd past keep-last-k each tick (in-flight
        # never evicted; live TokenStreams hold the request object)
        self.requests: Dict[int, FleetRequest] = {}
        self._chunks: Dict[int, int] = {}       # fid -> chunk seq applied
        self.finished_total = 0                 # completions ever, across GC
        self._next_fid = 0
        self._next_rid = 0
        self.requeues = 0
        # recent completion timestamps (monotonic) — the finish-rate window
        # behind FleetOverloadError.retry_after_s and the ingress backoff
        import collections as _collections

        self._finish_times = _collections.deque(maxlen=64)
        self._pending_done: List[FleetRequest] = []
        self._requeue_backlog: List[int] = []
        self._draining = False
        self._shut = False

        # rank-0 epoch for the children's clock_sync offsets
        try:
            self._store.set(f"{_trace.EPOCH_KEY_PREFIX}/0/epoch", repr(time.time()))
        except OSError:
            pass
        if spawn:
            for _ in range(int(replicas)):
                self._spawn_replica()
            self._wait_ready(list(self.replicas))
        self._emit_membership()

    # --------------------------------------------------------- attach mode
    @classmethod
    def attach(cls, endpoint: str, replicas: Optional[int] = None, *,
               ns: str = "serve", **kw) -> "ProcServingFleet":
        """Adopt replicas already booted by ``launch --serve`` (or another
        supervisor) instead of spawning: connect to the store at
        ``endpoint``, wait for the store-registered membership, and serve
        through them. ``replicas=None`` reads the member count the children
        registered. Supervision still applies — same-host pids are probed,
        everything else rides the stale-beat sweep."""
        kw = dict(kw, spawn=False)
        fleet = cls(endpoint=endpoint, ns=ns, replicas=1, **kw)
        if replicas is None:
            deadline = time.monotonic() + fleet.boot_timeout
            while True:
                n = int(fleet._store.add(f"procfleet/{ns}/members_n", 0))
                if n > 0:
                    replicas = n
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(f"procfleet attach: no members registered under ns {ns!r}")
                time.sleep(0.05)
        for rid in range(int(replicas)):
            fleet._adopt_replica(rid)
        fleet._wait_ready(list(fleet.replicas))
        fleet._emit_membership()
        return fleet

    # ------------------------------------------------------------ replicas
    def _make_replica(self, rid: int, proc) -> ProcReplica:
        conn_box: list = [None]
        rep = ProcReplica(
            rid, proc,
            in_ch=SocketChannel(self._store, channel_prefix(self.ns, rid, "in"),
                                "in", conn_box, rid=rid),
            out_ch=SocketChannel(self._store, channel_prefix(self.ns, rid, "out"),
                                 "out", conn_box, rid=rid),
            hb=Heartbeat(self._store, self.ns, rid),
            conn_box=conn_box)
        self.replicas[rid] = rep
        return rep

    def _maybe_connect_socket(self, rep: ProcReplica) -> None:
        """Dial the replica's advertised fast-path socket — exactly once,
        the first time it is seen ready (its sock key is published before
        the ready beat, so one attempt suffices). Failure or a missing
        advertisement just leaves the channels on the store transport."""
        if not self.use_sockets or rep.sock_tried or not rep.ready:
            return
        rep.sock_tried = True
        conn = connect_socket(self._store, self.ns, rep.rid)
        if conn is not None:
            rep.conn_box[0] = conn  # noqa: PTA104 (host-side serving transport, never traced)

    def _spawn_replica(self) -> ProcReplica:
        rid = self._next_rid
        self._next_rid += 1
        spec = {"rid": rid, "ns": self.ns, "endpoint": self.endpoint,
                "model": {"kind": "gpt", "seed": self.model_seed,
                          "config": self.model_config},
                "engine_kwargs": self.engine_kwargs,
                "beat_interval": self.beat_interval,
                "socket": self.use_sockets,
                "jax_config": current_jax_config()}
        # PADDLE_TRAINER_ID decorrelates the child's trace/span id streams
        # from the parent (rank 0) and its siblings — launcher discipline
        env = child_env({SPEC_ENV: json.dumps(spec),
                         "PADDLE_TRAINER_ID": str(rid + 1)})
        proc = subprocess.Popen(CHILD_CMD, env=env)
        return self._make_replica(rid, proc)

    def _adopt_replica(self, rid: int) -> ProcReplica:
        self._next_rid = max(self._next_rid, rid + 1)
        return self._make_replica(rid, None)

    def _wait_ready(self, rids: List[int]) -> None:
        """Block until every listed replica published a ready beat (the
        programs themselves still compile/AOT-load lazily on first
        dispatch). A child that exits while booting fails loudly here."""
        deadline = time.monotonic() + self.boot_timeout
        waiting = set(rids)
        while waiting:
            for rid in sorted(waiting):
                rep = self.replicas[rid]
                if rep.proc is not None and rep.proc.poll() is not None:
                    raise RuntimeError(
                        f"procfleet: replica {rid} exited rc={rep.proc.returncode} during boot")
                doc = rep.hb.read(timeout=0.05)
                if doc is not None and doc.get("ready"):
                    self._observe_beat(rep, doc)
                    self._maybe_connect_socket(rep)
                    waiting.discard(rid)  # noqa: PTA104 (host-side, never traced)
            if waiting and time.monotonic() > deadline:
                raise TimeoutError(
                    f"procfleet: replica(s) {sorted(waiting)} not ready after "
                    f"{self.boot_timeout:g}s")
            if waiting:
                time.sleep(0.05)

    def _alive(self) -> Dict[int, ProcReplica]:
        return {rid: rep for rid, rep in self.replicas.items() if rep.alive}

    def _emit_membership(self) -> None:
        alive = sorted(self._alive())
        dead = sorted(set(self.replicas) - set(alive))
        gauge_set("fleet.replicas_alive", len(alive))
        gauge_set("fleet.replicas_dead", len(dead))
        _runlog.emit("fleet", kind="membership", component="procfleet",
                     alive=alive, dead=dead)

    def scale_out(self, n: int = 1) -> List[int]:
        """Add ``n`` replica subprocesses live; with the AOT cache warm
        they serve their first request at ``infer.compiles == 0``."""
        new = [self._spawn_replica().rid for _ in range(int(n))]
        self._wait_ready(new)
        counter_inc("fleet.scale_outs", len(new))
        _runlog.emit("fleet", kind="scale_out", component="procfleet", replicas=new)
        self._emit_membership()
        return new

    def kill_replica(self, rid: int, reason: str = "killed") -> None:
        """Administrative SIGKILL — the real-process form of the chaos
        kill. In-flight work requeues onto the survivors."""
        rep = self.replicas[rid]
        rep.sigkill()
        if rep.alive:
            self._on_replica_death(rep, RuntimeError(reason))

    def child_counters(self) -> Dict[int, Dict[str, int]]:
        """Per-replica ``infer.*`` counters as last self-reported through
        heartbeats — compiles/AOT-hits are per-PROCESS state, so the warm
        boot pin (``compiles == 0``) reads them from here, not from the
        parent's registry."""
        return {rid: dict(rep.counters) for rid, rep in self.replicas.items()}

    # ----------------------------------------------------------- admission
    def queue_depth(self) -> int:
        """Total in-flight requests across alive replicas — the parent's
        synchronous view (a child's internal queue split arrives on its
        next tick message), and what admission compares to
        ``max_queue_depth``."""
        return sum(rep.load() for rep in self._alive().values())

    def finish_rate(self) -> Optional[float]:
        """Recent completions per second over the sliding finish window
        (None until two completions exist) — the denominator of
        :func:`~.fleet.retry_after_estimate`."""
        t = self._finish_times
        if len(t) < 2 or t[-1] <= t[0]:
            return None
        return (len(t) - 1) / (t[-1] - t[0])

    def transport_lag(self) -> Dict[str, float]:
        """Transport-health watermarks for ingress backpressure:
        ``out_backlog`` is the deepest unacknowledged fast-path send window
        across alive replicas (how far the wire is behind the writers) and
        ``beat_age_s`` the stalest alive heartbeat on the parent's clock.
        Either climbing past the ingress watermarks means the fleet is
        falling behind its transport — shed before the queues do it."""
        alive = [rep for rep in self.replicas.values() if rep.alive]
        beat = max(((time.monotonic() - rep.last_beat) for rep in alive),
                   default=0.0)
        backlog = max((float(rep.in_ch.backlog() + rep.out_ch.backlog())
                       for rep in alive), default=0.0)
        return {"out_backlog": backlog, "beat_age_s": float(beat)}

    def tokens_so_far(self, fid: int) -> List[int]:
        """Live view of ``fid``'s generated tokens — the append-only chunk
        ledger, which grows as stream chunks arrive. The ingress streams
        from this (same cursor discipline as :class:`TokenStream`)."""
        return list(self.requests[fid].tokens)

    def submit(self, prompt, max_new_tokens: int = 16,
               eos_token_id: Optional[int] = None, seed: int = 0,
               deadline_s: Optional[float] = None,
               replica: Optional[int] = None, stream: bool = False):
        """Route one prompt into the fleet. Returns the fleet request id,
        or — with ``stream=True`` — a :class:`TokenStream` yielding
        in-order token chunks as they arrive (``.fid`` has the id).
        Semantics otherwise match :meth:`ServingFleet.submit`: admission
        control first, prefix-affinity placement (``replica=`` pins),
        ``deadline_s`` bounding total time across requeues."""
        alive = self._alive()
        if not alive:
            raise FleetDrainedError(sorted(
                fid for fid, r in self.requests.items()
                if r.status in ("queued", "prefilling", "running")))
        depth = self.queue_depth()
        if depth >= self.max_queue_depth:
            counter_inc("fleet.sheds")
            _runlog.emit("fleet", kind="shed", component="procfleet",
                         queued=depth, limit=self.max_queue_depth)
            raise FleetOverloadError(
                depth, self.max_queue_depth, len(alive),
                retry_after_s=retry_after_estimate(depth, self.finish_rate()))
        if replica is not None:
            if replica not in alive:
                raise ValueError(f"replica {replica} is not alive")
            rid, reason = int(replica), "pinned"
        else:
            rid, reason = self.router.place(
                prompt, {r: rep.load() for r, rep in alive.items()})
            counter_inc("fleet.routed_affinity" if reason == "affinity"
                        else "fleet.routed_load")
        fid = self._next_fid
        self._next_fid += 1
        freq = FleetRequest(fid, prompt, max_new_tokens, eos_token_id, seed,
                            deadline_s, trace_id=_trace.new_trace_id("fleet"))
        self.requests[fid] = freq
        self._chunks[fid] = 0
        _runlog.emit("fleet", kind="submitted", component="procfleet", id=fid,
                     trace=freq.trace_id, prompt_tokens=len(freq.prompt),
                     max_new_tokens=freq.max_new_tokens, stream=bool(stream))
        self._place(freq, rid, reason)
        counter_inc("fleet.requests_submitted")
        gauge_set("fleet.queue_depth", self.queue_depth())
        return TokenStream(self, fid) if stream else fid

    def cancel(self, fid: int, status: str = "cancelled") -> bool:
        """Forward a cancellation to the replica holding ``fid``. The
        child's scheduler frees the slot mid-decode; the terminal status
        arrives back on its next tick."""
        freq = self.requests.get(fid)
        if freq is None or freq.status in _TERMINAL or freq.replica is None:
            return False
        rep = self.replicas.get(freq.replica)
        if rep is None or not rep.alive:
            return False
        rep.in_ch.send("cancel", fid=fid, status=status)
        return True

    def _place(self, freq: FleetRequest, rid: int, reason: str,
               deadline_s: Optional[float] = "unset") -> None:
        """Ship ``freq`` to replica ``rid`` over RPC and index it in the
        parent ledger. The envelope carries the trace id so the child's
        request/span events join the same distributed trace."""
        rep = self.replicas[rid]
        if deadline_s == "unset":
            deadline_s = freq.deadline_s
        rep.in_ch.send(
            "submit", fid=freq.fid, prompt=[int(t) for t in freq.prompt],
            max_new_tokens=freq.max_new_tokens, eos_token_id=freq.eos_token_id,
            seed=freq.seed, deadline_s=deadline_s, trace=freq.trace_id)
        self.router.register(freq.prompt, rid)
        freq.replica = rid
        freq.status = "running"
        rep.assigned.add(freq.fid)
        _runlog.emit("fleet", kind="placed", component="procfleet", id=freq.fid,
                     replica=rid, reason=reason, attempt=freq.attempts,
                     trace=freq.trace_id)

    # ----------------------------------------------------------- the loop
    def step(self) -> List[FleetRequest]:
        """One supervision tick: harvest every alive replica's out-channel
        (tick results, token chunks, completions), fire any armed SIGKILL
        chaos, then run the two death detectors — process liveness and the
        stale-beat sweep. Returns fleet requests finished this tick."""
        done: List[FleetRequest] = self._pending_done
        self._pending_done = []
        for rid, rep in list(self.replicas.items()):  # noqa: PTA102 (host-side serving loop, never traced)
            if not rep.alive:
                continue
            self._maybe_connect_socket(rep)
            try:
                msgs = rep.out_ch.recv()
            except (TimeoutError, OSError) as exc:
                self._drain_and_die(rep, exc, done)
                continue  # noqa: PTA103 (host-side serving loop, never traced)
            self._apply(rep, msgs, done)
            if chaos.replica_sigkill_due(rid, rep.ticks):
                rep.sigkill()  # a real kill -9, mid-decode
            if not rep.process_alive():
                rc = rep.proc.returncode if rep.proc is not None else None
                self._drain_and_die(rep, RuntimeError(
                    f"replica process died (rc={rc})"), done)
                continue  # noqa: PTA103 (host-side serving loop, never traced)
            self._sweep_beat(rep, done)
        gauge_set("fleet.queue_depth", self.queue_depth())
        beats = [time.monotonic() - rep.last_beat
                 for rep in self.replicas.values() if rep.alive]
        if beats:
            gauge_set("fleet.heartbeat_staleness_seconds", max(beats))
        _slo.on_tick()  # judgment layer: single flag check until armed
        self._gc_ledger(protect={r.fid for r in done})
        if _sanitizer.enabled():
            # runtime PTA305: post-GC the ledger is keep-last-k + in-flight
            _sanitizer.note_ledger(
                "procfleet", "requests", len(self.requests),
                bound=2 * self.keep_finished + self.max_queue_depth)
        return done

    def _gc_ledger(self, protect=()) -> None:
        """Keep-last-k GC of delivered requests (and their chunk cursors):
        evict the OLDEST terminal entries past ``keep_finished``. In-flight
        entries are untouched — requeue/exactly-once accounting reads the
        ledger only for live fids — and this tick's completions are
        protected so :meth:`step`'s return is harvested before eviction."""
        protect = set(protect)
        terminal = [fid for fid, r in self.requests.items()
                    if r.status in _TERMINAL and fid not in protect]
        overflow = len(terminal) - self.keep_finished
        for fid in terminal[:max(0, overflow)]:
            del self.requests[fid]
            self._chunks.pop(fid, None)  # noqa: PTA104 (host-side serving loop)

    def _sweep_beat(self, rep: ProcReplica, done: List[FleetRequest]) -> None:
        doc = rep.hb.read(timeout=0.02)
        if doc is not None:
            self._observe_beat(rep, doc)
        if (self.heartbeat_timeout and rep.ready
                and time.monotonic() - rep.last_beat > self.heartbeat_timeout):
            # process is up but the beat counter stopped moving: a zombie
            # (FLAGS_chaos_replica_hang_ms, a wedged dispatch). Same
            # protocol as death — and the parent reaps the husk.
            self._drain_and_die(rep, TimeoutError(
                f"heartbeat lost: no beat for > {self.heartbeat_timeout:g}s"),
                done)

    def _observe_beat(self, rep: ProcReplica, doc: dict) -> None:
        if doc.get("n", 0) != rep.beat_n:
            rep.beat_n = doc.get("n", 0)  # noqa: PTA104 (host-side, never traced)
            rep.last_beat = time.monotonic()  # noqa: PTA104 (host-side, never traced)
        rep.ready = rep.ready or bool(doc.get("ready"))
        rep.pid = doc.get("pid", rep.pid)
        rep.host = doc.get("host", rep.host)
        rep.counters = {k: int(doc.get(k, 0))
                        for k in ("compiles", "aot_cache_hits")}
        rep.reported_load = int(doc.get("load", rep.reported_load))

    def _apply(self, rep: ProcReplica, msgs: List[dict],
               done: List[FleetRequest]) -> None:
        for m in msgs:
            kind = m["kind"]
            if kind == "tick":
                rep.ticks += 1  # noqa: PTA104 (host-side, never traced)
                rep.reported_load = int(m.get("load", rep.reported_load))  # noqa: PTA104 (host-side, never traced)
            elif kind == "chunk":
                self._apply_chunk(rep, m)
            elif kind == "finished":
                self._apply_finished(rep, m, done)
            elif kind == "bye":
                rep.draining = True  # noqa: PTA104 (host-side, never traced)

    def _apply_chunk(self, rep: ProcReplica, m: dict) -> None:
        """Extend the delivery ledger with one streamed chunk. The ledger
        is append-only and the channel is ordered, so the only interesting
        case is the post-requeue replay: a survivor re-streams from
        position 0 and only the suffix past what was already delivered is
        appended — no duplicates, no gaps, no reordering, ever."""
        freq = self.requests.get(m["fid"])
        if freq is None or freq.status in _TERMINAL or freq.replica != rep.rid:
            return
        start, toks = int(m["start"]), m["tokens"]
        have = len(freq.tokens)
        if start > have:
            return  # a gap can only mean a lost writer; the replay heals it
        new = toks[have - start:]
        if not new:
            return
        if freq.first_token_ts is None:
            freq.first_token_ts = time.perf_counter()  # noqa: PTA104 (host-side, never traced)
        freq.tokens.extend(int(t) for t in new)
        self._chunks[freq.fid] = self._chunks.get(freq.fid, 0) + 1
        counter_inc("fleet.stream_chunks")

    def _apply_finished(self, rep: ProcReplica, m: dict,
                        done: List[FleetRequest]) -> None:
        fid = m["fid"]
        freq = self.requests.get(fid)
        if freq is None or fid not in rep.assigned:
            return
        rep.assigned.discard(fid)
        status = m["status"]
        if status != "finished":
            freq.status = status  # noqa: PTA104 (host-side serving loop, never traced)
            freq.finished_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop, never traced)
            if status == "deadline_exceeded":
                counter_inc("fleet.deadline_hits")
            _runlog.emit("fleet",
                         kind=("deadline" if status == "deadline_exceeded"
                               else "cancelled"),
                         component="procfleet", id=fid, replica=rep.rid,
                         status=status, trace=freq.trace_id)
            return
        if freq.status == "finished":
            return  # exactly-once: the ledger was already written
        final = [int(t) for t in m["tokens"]]
        if final[:len(freq.tokens)] != list(freq.tokens):
            # bitwise contract violated — never silently rewrite what a
            # stream already delivered; surface it for the postmortem
            _runlog.emit("fleet", kind="stream_divergence", component="procfleet",
                         id=fid, replica=rep.rid, delivered=len(freq.tokens),
                         trace=freq.trace_id)
        freq.tokens.extend(final[len(freq.tokens):])  # noqa: PTA104 (host-side serving loop, never traced)
        freq.status = "finished"  # noqa: PTA104 (host-side serving loop, never traced)
        freq.finished_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop, never traced)
        if freq.first_token_ts is None:
            freq.first_token_ts = freq.finished_ts  # noqa: PTA104 (host-side serving loop, never traced)
        rep.completed += 1  # noqa: PTA104 (host-side serving loop, never traced)
        self.finished_total += 1
        self._finish_times.append(time.monotonic())  # noqa: PTA305 (bounded deque, maxlen=64)
        counter_inc("fleet.requests_completed")
        observe("fleet.latency_seconds", freq.total_seconds)
        _runlog.emit("fleet", kind="finished", component="procfleet", id=fid,
                     replica=rep.rid, new_tokens=len(freq.tokens),
                     seconds=freq.total_seconds, attempts=freq.attempts,
                     chunks=self._chunks.get(fid, 0), trace=freq.trace_id)
        done.append(freq)  # noqa: PTA104 (host-side serving loop, never traced)

    # ------------------------------------------------------ death + requeue
    def _drain_and_die(self, rep: ProcReplica, exc: BaseException,
                       done: List[FleetRequest]) -> None:
        """Final harvest, then the death protocol. Anything the child
        published before dying — including a completion — is applied
        first: a request it finished is DELIVERED from that harvest and
        never replayed (the exactly-once seam for real process death)."""
        try:
            self._apply(rep, rep.out_ch.recv(), done)
        except (TimeoutError, OSError):
            pass
        if rep.alive:
            self._on_replica_death(rep, exc)

    def _on_replica_death(self, rep: ProcReplica, exc: BaseException) -> None:
        """Mark dead, reap, forget chains, requeue from the parent ledger.
        Re-entrant: a survivor dying while absorbing requeued work lands
        its pending fids on the shared backlog and returns — the outermost
        drain loop owns placement, so cascade kills keep full
        ``FleetDrainedError`` lost-fid accounting (same protocol as
        ``ServingFleet._on_replica_death``)."""
        rep.alive = False
        rep.death_reason = f"{type(exc).__name__}: {exc}"
        counter_inc("fleet.replica_deaths")
        rep.sigkill()  # reap the husk: hung children must not linger
        if rep.conn_box[0] is not None:
            rep.conn_box[0].kill("replica dead")
        self.router.forget_replica(rep.rid)
        pending = sorted(rep.assigned)
        rep.assigned = set()
        lost_traces = sorted({t for t in (
            self.requests[fid].trace_id for fid in pending) if t is not None})
        _runlog.emit("fleet", kind="replica_dead", component="procfleet",
                     replica=rep.rid, reason=rep.death_reason, pid=rep.pid,
                     inflight=len(pending), traces=lost_traces)
        _flightrec.dump("replica_death", exc, replica=rep.rid, pid=rep.pid,
                        inflight=pending, traces=lost_traces)
        self._emit_membership()
        self._requeue_backlog.extend(pending)
        if self._draining:
            return  # the outermost drain loop absorbs the new backlog
        self._draining = True
        try:
            lost: List[int] = []
            while self._requeue_backlog:
                fid = self._requeue_backlog.pop(0)
                survivors = self._alive()
                if not survivors:
                    lost.append(fid)  # noqa: PTA104 (host-side serving loop, never traced)
                    continue
                self._requeue(self.requests[fid], survivors)
            if lost:
                raise FleetDrainedError(sorted(lost))
        finally:
            self._draining = False

    def _requeue(self, freq: FleetRequest,
                 survivors: Dict[int, ProcReplica]) -> None:
        """Replay one request lost to a replica death on a survivor:
        original prompt + seed (bitwise-identical tokens — sampling folds
        on request seed and absolute position, never slot or process) with
        the REMAINING deadline. Tokens already stream-delivered stay in
        the ledger; the replay's chunks only extend past them."""
        remaining = freq.deadline_s
        if freq.deadline_s is not None:
            remaining = freq.deadline_s - (time.perf_counter() - freq.submitted_ts)
            if remaining <= 0:
                freq.status = "deadline_exceeded"  # noqa: PTA104 (host-side serving loop, never traced)
                freq.finished_ts = time.perf_counter()  # noqa: PTA104 (host-side serving loop, never traced)
                counter_inc("fleet.deadline_hits")
                _runlog.emit("fleet", kind="deadline", component="procfleet",
                             id=freq.fid, replica=freq.replica,
                             status="deadline_exceeded", trace=freq.trace_id)
                return
        freq.attempts += 1
        self.requeues += 1
        counter_inc("fleet.requeues")
        rid, reason = self.router.place(
            freq.prompt, {r: rep.load() for r, rep in survivors.items()})
        _runlog.emit("fleet", kind="requeue", component="procfleet", id=freq.fid,
                     replica=rid, from_replica=freq.replica, reason=reason,
                     trace=freq.trace_id)
        self._place(freq, rid, f"requeue/{reason}", deadline_s=remaining)

    # ------------------------------------------------------------- driving
    def _outstanding(self) -> bool:
        return any(r.status in ("queued", "prefilling", "running")
                   for r in self.requests.values())

    def run(self, max_ticks: Optional[int] = None,
            timeout_s: Optional[float] = None) -> Dict[int, FleetRequest]:
        """Drive :meth:`step` until every accepted request reaches a
        terminal status (or ``max_ticks``/``timeout_s``); returns
        ``{fid: FleetRequest}`` for every completion of the run —
        accumulated across ticks, so requests the keep-last-k ledger GC has
        since evicted are still returned."""
        done = {fid: r for fid, r in self.requests.items()
                if r.status == "finished"}
        ticks = 0
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while self._outstanding() and self._alive():
            for r in self.step():
                done[r.fid] = r  # noqa: PTA104 (host-side serving loop)
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
            if deadline is not None and time.monotonic() > deadline:
                break
            time.sleep(self.poll_s)
        done.update({fid: r for fid, r in self.requests.items()
                     if r.status == "finished"})
        return done

    # ------------------------------------------------------------ teardown
    def shutdown(self, grace: float = 5.0) -> None:
        """Drain: ask every alive child to exit, wait ``grace``, then
        escalate to SIGTERM/SIGKILL; finally close the store."""
        if self._shut:
            return
        self._shut = True
        for rep in self._alive().values():
            try:
                rep.in_ch.send("drain")
            except OSError:
                pass
        deadline = time.monotonic() + grace
        procs = [rep.proc for rep in self.replicas.values() if rep.proc is not None]
        while time.monotonic() < deadline and any(p.poll() is None for p in procs):
            time.sleep(0.05)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        try:
            self._raw_store.close()
        except OSError:
            pass

    def __enter__(self) -> "ProcServingFleet":
        return self

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False

    # ------------------------------------------------------------- summary
    def stats(self) -> dict:
        alive = self._alive()
        return {
            "replicas": len(self.replicas),
            "alive": sorted(alive),
            "dead": sorted(set(self.replicas) - set(alive)),
            "requests": len(self.requests),
            "finished": sum(1 for r in self.requests.values()
                            if r.status == "finished"),
            "finished_total": self.finished_total,
            "requeues": self.requeues,
            "queue_depth": self.queue_depth(),
            "router": self.router.stats(),
            "per_replica": {rid: {
                "alive": rep.alive,
                "pid": rep.pid,
                "ticks": rep.ticks,
                "completed": rep.completed,
                "load": rep.load(),
                "counters": dict(rep.counters),
                "death_reason": rep.death_reason,
                "transport": {
                    "socket": (rep.conn_box[0] is not None
                               and rep.conn_box[0].alive),
                    "socket_msgs": rep.in_ch.socket_msgs + rep.out_ch.socket_msgs,
                    "store_msgs": rep.in_ch.store_msgs + rep.out_ch.store_msgs,
                    "fallbacks": rep.in_ch.fallbacks + rep.out_ch.fallbacks,
                },
            } for rid, rep in self.replicas.items()},
        }


if __name__ == "__main__":
    sys.exit(replica_main())
