"""paddle_tpu.inference — the serving tier.

Parity: ``paddle.inference`` (reference AnalysisPredictor
paddle/fluid/inference/api/analysis_predictor.h:93, Config
paddle_analysis_config.h, Tensor handles paddle_tensor.h). TPU-first design:
the serialized model is a StableHLO artifact (jax.export) produced by
``paddle.static.save_inference_model`` or ``paddle.jit.save`` (the pickled
``.pdiparams`` metadata / ``.pdparams`` state dicts remain as the legacy
non-executable format); "IR pass pipeline + TensorRT subgraphs" collapse
into XLA compilation at load. The :class:`Predictor` compiles ahead of time
through the observability AOT ``lower().compile()`` path, so the first
``run()`` is a dispatch, not a trace, and ``explain()`` answers
cost/memory questions per compiled specialization.

On top of the artifact predictor sit the serving-engine pieces:

- :class:`DecodeEngine` (``.engine``) — static-shape device-resident KV
  cache decode: prefill + decode-step as exactly TWO compiled programs
  with donated cache buffers;
- :class:`ContinuousBatchingScheduler` (``.scheduler``) — in-flight
  batching: requests admitted into free batch slots mid-stream, bucketed
  prefill padding, per-request deadlines + mid-decode cancellation,
  request-level telemetry;
- :class:`ServingFleet` (``.fleet``) + :class:`Router` (``.router``) — the
  fault-tolerant tier: N engine replicas behind prefix-cache-affinity
  placement, heartbeat health tracking, kill-safe drain/requeue
  (exactly-once, bitwise-identical completions through a mid-stream
  replica death), queue-depth load shedding, and AOT-warm scale-out;
- :class:`ServingIngress` (``.ingress``) — the stdlib HTTP/1.1 front
  door over either fleet: per-token chunked streaming off the same
  exactly-once ledger, idempotency keys, deadline propagation,
  disconnect→cancel, 429/503 backpressure with ``Retry-After``, and
  SIGTERM graceful drain; the cross-process fleet's hot channels ride a
  direct socket fast path (``.rpc.SocketChannel``) that degrades back to
  the TCPStore transport on any socket fault without losing a chunk.

Backend placement is honest: ``Config.enable_use_gpu`` records the REQUEST
and the resolved backend is whatever the runtime actually has (TPU when
present — the accelerator alias — else CPU); ``Config.summary()``,
``Predictor.backend`` and :func:`get_version` report the resolution instead
of silently aliasing.
"""
from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .engine import DecodeEngine, default_buckets
from .fleet import (
    EngineReplica,
    FleetDrainedError,
    FleetOverloadError,
    FleetRequest,
    ServingFleet,
    retry_after_estimate,
)
from .ingress import ServingIngress
from .prefix_cache import PrefixCache
from .procfleet import ProcReplica, ProcServingFleet, TokenStream
from .router import Router
from .scheduler import ContinuousBatchingScheduler, Request

__all__ = [
    "Config", "Predictor", "create_predictor", "PredictorTensor",
    "DecodeEngine", "ContinuousBatchingScheduler", "Request",
    "PrefixCache", "default_buckets", "get_version",
    "ServingFleet", "EngineReplica", "FleetRequest", "Router",
    "FleetOverloadError", "FleetDrainedError",
    "ProcServingFleet", "ProcReplica", "TokenStream",
    "ServingIngress", "retry_after_estimate",
]


def get_version() -> str:
    """Version/introspection string (reference ``paddle.inference``'s
    get_version/get_trt_compile_version): runtime versions plus the
    backends actually present — what placement decisions resolve against."""
    import paddle_tpu

    try:
        platforms = sorted({d.platform for d in jax.devices()})
    except RuntimeError:
        platforms = []
    return (f"paddle_tpu {getattr(paddle_tpu, '__version__', '0.0.0')}; "
            f"jax {jax.__version__}; default_backend={_default_backend()}; "
            f"platforms={','.join(platforms) or 'none'}")


def _default_backend() -> str:
    try:
        return jax.default_backend()
    except RuntimeError:
        return "unknown"


class Config:
    """reference AnalysisConfig: model paths + backend knobs.

    Device knobs record the *request*; :meth:`resolved_backend` reports what
    the runtime will actually use. ``enable_use_gpu`` on a TPU system
    resolves to the TPU (the accelerator alias, now recorded instead of
    silent); on a CPU-only system it resolves to CPU.
    """

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # accept either a prefix ("model") or explicit "model.pdmodel"
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.prefix = prog_file
        self.params_file = params_file
        self._requested_device: Optional[str] = None  # None = runtime default
        self._memory_optim = True

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        requested, memopt = self._requested_device, self._memory_optim
        self.__init__(prog_file, params_file)
        self._requested_device, self._memory_optim = requested, memopt

    # ------------------------------------------------------------- devices
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._requested_device = "gpu"

    def disable_gpu(self):
        self._requested_device = "cpu"

    def use_gpu(self) -> bool:
        """Whether an accelerator was requested (reference API shape)."""
        return self._requested_device == "gpu"

    def requested_device(self) -> Optional[str]:
        return self._requested_device

    def resolved_backend(self) -> str:
        """The backend runs will actually execute on. ``cpu`` when CPU was
        requested; otherwise the runtime's default backend (TPU when
        present). A ``gpu`` request on a non-GPU runtime resolves to that
        default — recorded here, surfaced by summary()/Predictor."""
        if self._requested_device == "cpu":
            return "cpu"
        return _default_backend()

    def summary(self) -> str:
        """Human-readable config table (reference Config.summary), including
        the requested-vs-resolved placement so accepted aliases are visible."""
        requested = self._requested_device or "default"
        resolved = self.resolved_backend()
        rows = [
            ("model prefix", str(self.prefix)),
            ("params file", str(self.params_file)),
            ("requested device", requested),
            ("resolved backend", resolved),
            ("memory optim", str(self._memory_optim)),
        ]
        if self._requested_device == "gpu" and resolved != "gpu":
            rows.append(("placement note", f"gpu requested; runtime has {resolved} (accelerator alias)"))
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k:<{w}}  {v}" for k, v in rows)

    # ---------------------------------------------------- accepted no-ops
    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):  # XLA always optimizes
        pass

    def set_cpu_math_library_num_threads(self, n):  # XLA-managed
        pass

    def enable_tensorrt_engine(self, *a, **k):  # no TRT on TPU; XLA compiles
        pass

    def model_dir(self):
        return str(Path(self.prefix).parent) if self.prefix else ""


class PredictorTensor:
    """Input/output handle (reference paddle_infer::Tensor): stage numpy in,
    read numpy out."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, data: np.ndarray) -> None:
        if not self._is_input:
            raise RuntimeError(f"{self.name} is an output handle")
        self._owner._inputs[self.name] = jnp.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(f"{self.name} is an input handle")
        out = self._owner._outputs.get(self.name)
        if out is None:
            raise RuntimeError("run() has not produced outputs yet")
        return np.asarray(out)

    def reshape(self, shape):  # reference API; shape comes from copy_from_cpu
        pass

    @property
    def shape(self):
        src = self._owner._inputs if self._is_input else self._owner._outputs
        v = src.get(self.name)
        return list(v.shape) if v is not None else None


class Predictor:
    """Loads a .pdmodel StableHLO artifact and serves it AOT-compiled.

    Each distinct input-shape signature is lowered and compiled ONCE through
    ``jit(...).lower().compile()`` (the observability introspect path) —
    the retained XLA Compiled handle backs :meth:`explain` and run() is a
    pure dispatch afterwards. The resolved backend (see
    :meth:`Config.resolved_backend`) is honored: inputs are placed on that
    backend's device, and :attr:`backend` / :meth:`get_resolved_backend`
    report the actual placement.
    """

    def __init__(self, config: Config):
        if not config.prefix:
            raise ValueError("Config has no model path; call set_model(prefix)")
        # prefix + ".pdmodel" (plain concatenation: a dotted prefix like
        # "net.v2" must not have its suffix replaced)
        model_path = Path(str(config.prefix) + ".pdmodel")
        if not model_path.exists():
            raise FileNotFoundError(f"{model_path} not found")
        self.config = config
        self._exported = jax.export.deserialize(model_path.read_bytes())
        meta_path = Path(str(config.prefix) + ".pdiparams")
        if meta_path.exists():
            # legacy pickle metadata sidecar (feed/fetch names + shapes)
            self._meta = pickle.loads(meta_path.read_bytes())
        else:  # artifact without metadata: positional names
            self._meta = {
                "feed_names": [f"input_{i}" for i in range(len(self._exported.in_avals))],
                "fetch_names": [f"output_{i}" for i in range(len(self._exported.out_avals))],
            }
        self.backend = config.resolved_backend()
        try:
            self._device = jax.devices(self.backend)[0]
        except RuntimeError:
            self._device = None  # backend absent: let jax place on default
        self._jit = jax.jit(self._exported.call)
        self._compiled: Dict[tuple, Any] = {}
        self._specializations: List[dict] = []
        self._inputs: Dict[str, jax.Array] = {}
        self._outputs: Dict[str, jax.Array] = {}
        from ..observability import runlog as _runlog

        _runlog.emit("predictor_load", component="infer", prefix=str(config.prefix),
                     backend=self.backend,
                     requested=config.requested_device() or "default")

    def get_resolved_backend(self) -> str:
        """The backend run() actually executes on (honest placement — an
        accepted ``enable_use_gpu`` on TPU reports 'tpu', not 'gpu')."""
        return self.backend

    # ------------------------------------------------------------- handles
    def get_input_names(self) -> List[str]:
        return list(self._meta["feed_names"])

    def get_output_names(self) -> List[str]:
        return list(self._meta["fetch_names"])

    def get_input_handle(self, name: str) -> PredictorTensor:
        if name not in self._meta["feed_names"]:
            raise KeyError(f"unknown input {name!r}; inputs: {self._meta['feed_names']}")
        return PredictorTensor(name, self, is_input=True)

    get_input_tensor = get_input_handle

    def get_output_handle(self, name: str) -> PredictorTensor:
        if name not in self._meta["fetch_names"]:
            raise KeyError(f"unknown output {name!r}; outputs: {self._meta['fetch_names']}")
        return PredictorTensor(name, self, is_input=False)

    get_output_tensor = get_output_handle

    # ----------------------------------------------------------------- run
    def _compiled_for(self, vals):
        """The AOT-compiled executable for this input-shape signature,
        compiling (and recording cost/compile telemetry) on first sight.
        Falls back to the plain jitted call when AOT is unavailable."""
        sig = tuple((tuple(v.shape), str(v.dtype)) for v in vals)
        entry = self._compiled.get(sig)
        if entry is None:
            from ..observability import introspect as _introspect
            from ..observability import runlog as _runlog
            from ..observability import span as _span
            from ..profiler import counter_inc

            label = "predictor/" + ",".join(f"{d}{list(s)}" for s, d in sig[:4])
            with _span("infer.compile"):
                compiled, info = _introspect.aot_compile(self._jit, tuple(vals))
            entry = compiled if compiled is not None else self._jit
            self._compiled[sig] = entry  # noqa: PTA305 (compile cache keyed by bucketed signature — bounded by the shape ladder + recompile-churn sentinel)
            counter_inc("infer.compiles")
            info["label"] = label
            info["kind"] = "predictor"
            self._specializations.append(info)  # noqa: PTA305 (one entry per compiled signature — bounded by the shape ladder + recompile-churn sentinel)
            _runlog.emit("compile", component="infer", label=label,
                         seconds=info.get("compile_seconds"),
                         flops=info.get("flops"),
                         bytes_accessed=info.get("bytes_accessed"),
                         peak_bytes=info.get("peak_bytes"))
        return entry, sig

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either positional ``inputs`` or previously staged
        copy_from_cpu handles."""
        from ..observability import span as _span
        from ..profiler import counter_inc

        feed_names = self._meta["feed_names"]
        if inputs is not None:
            vals = [jnp.asarray(x) for x in inputs]
        else:
            missing = [n for n in feed_names if n not in self._inputs]
            if missing:
                raise RuntimeError(f"inputs not staged: {missing}")
            vals = [self._inputs[n] for n in feed_names]
        if self._device is not None:
            vals = [jax.device_put(v, self._device) for v in vals]
        entry, sig = self._compiled_for(vals)
        with _span("infer.run"):
            try:
                outs = entry(*vals)
            except (TypeError, ValueError):
                if entry is self._jit:
                    raise
                # AOT executables validate avals strictly; on drift fall
                # back to the jitted path permanently for this signature
                self._compiled[sig] = self._jit
                outs = self._jit(*vals)
        counter_inc("infer.runs")
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        self._outputs = dict(zip(self._meta["fetch_names"], outs))
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def generate(self, ids, seed: int = 0) -> np.ndarray:
        """Serve a decoder artifact (``GPTForPretraining.export_decoder``):
        runs the exported prefill + KV-cache token loop. ``ids`` must match
        the artifact's fixed ``prompt_len``; returns
        ``[b, prompt_len + max_new_tokens]`` int32 tokens."""
        dec = self._meta.get("decoder")
        if not dec:
            raise RuntimeError(
                "this artifact has no decoder metadata; export it with "
                "GPTForPretraining.export_decoder (or serve a live model "
                "through paddle_tpu.inference.DecodeEngine)")
        ids = np.asarray(ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if ids.shape[1] != dec["prompt_len"]:
            raise ValueError(f"prompt length {ids.shape[1]} != artifact prompt_len "
                             f"{dec['prompt_len']} (pad/bucket on the client side)")
        (tokens,) = self.run([ids, np.int32(seed)])
        return np.asarray(tokens)

    def explain(self) -> List[dict]:
        """Per-specialization XLA cost rows captured at AOT compile; render
        with ``observability.format_cost_table``."""
        return list(self._specializations)

    def clear_intermediate_tensor(self):
        self._inputs.clear()
        self._outputs.clear()


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
