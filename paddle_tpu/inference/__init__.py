"""paddle_tpu.inference — deployment API.

Parity: ``paddle.inference`` (reference AnalysisPredictor
paddle/fluid/inference/api/analysis_predictor.h:93, Config
paddle_analysis_config.h, Tensor handles paddle_tensor.h). TPU-first design:
the serialized model is a StableHLO artifact (jax.export) produced by
``paddle.static.save_inference_model`` or ``paddle.jit.save``; "IR pass
pipeline + TensorRT subgraphs" collapse into XLA compilation at load, so
Config's optimization toggles are accepted no-ops.
"""
from __future__ import annotations

import pickle
from pathlib import Path
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """reference AnalysisConfig: model paths + backend knobs."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # accept either a prefix ("model") or explicit "model.pdmodel"
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.prefix = prog_file
        self.params_file = params_file
        self._device = "tpu"
        self._memory_optim = True

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self.__init__(prog_file, params_file)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator alias

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):  # XLA always optimizes
        pass

    def set_cpu_math_library_num_threads(self, n):  # XLA-managed
        pass

    def enable_tensorrt_engine(self, *a, **k):  # no TRT on TPU; XLA compiles
        pass

    def model_dir(self):
        return str(Path(self.prefix).parent) if self.prefix else ""


class PredictorTensor:
    """Input/output handle (reference paddle_infer::Tensor): stage numpy in,
    read numpy out."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, data: np.ndarray) -> None:
        if not self._is_input:
            raise RuntimeError(f"{self.name} is an output handle")
        self._owner._inputs[self.name] = jnp.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(f"{self.name} is an input handle")
        out = self._owner._outputs.get(self.name)
        if out is None:
            raise RuntimeError("run() has not produced outputs yet")
        return np.asarray(out)

    def reshape(self, shape):  # reference API; shape comes from copy_from_cpu
        pass

    @property
    def shape(self):
        src = self._owner._inputs if self._is_input else self._owner._outputs
        v = src.get(self.name)
        return list(v.shape) if v is not None else None


class Predictor:
    """Loads a .pdmodel StableHLO artifact and runs it on the default device
    (TPU when present). First run() compiles; later runs hit the XLA cache."""

    def __init__(self, config: Config):
        if not config.prefix:
            raise ValueError("Config has no model path; call set_model(prefix)")
        # prefix + ".pdmodel" (plain concatenation: a dotted prefix like
        # "net.v2" must not have its suffix replaced)
        model_path = Path(str(config.prefix) + ".pdmodel")
        if not model_path.exists():
            raise FileNotFoundError(f"{model_path} not found")
        self.config = config
        self._exported = jax.export.deserialize(model_path.read_bytes())
        meta_path = Path(str(config.prefix) + ".pdiparams")
        if meta_path.exists():
            self._meta = pickle.loads(meta_path.read_bytes())
        else:  # artifact without metadata: positional names
            self._meta = {
                "feed_names": [f"input_{i}" for i in range(len(self._exported.in_avals))],
                "fetch_names": [f"output_{i}" for i in range(len(self._exported.out_avals))],
            }
        self._inputs: Dict[str, jax.Array] = {}
        self._outputs: Dict[str, jax.Array] = {}

    # ------------------------------------------------------------- handles
    def get_input_names(self) -> List[str]:
        return list(self._meta["feed_names"])

    def get_output_names(self) -> List[str]:
        return list(self._meta["fetch_names"])

    def get_input_handle(self, name: str) -> PredictorTensor:
        if name not in self._meta["feed_names"]:
            raise KeyError(f"unknown input {name!r}; inputs: {self._meta['feed_names']}")
        return PredictorTensor(name, self, is_input=True)

    get_input_tensor = get_input_handle

    def get_output_handle(self, name: str) -> PredictorTensor:
        if name not in self._meta["fetch_names"]:
            raise KeyError(f"unknown output {name!r}; outputs: {self._meta['fetch_names']}")
        return PredictorTensor(name, self, is_input=False)

    get_output_tensor = get_output_handle

    # ----------------------------------------------------------------- run
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either positional ``inputs`` or previously staged
        copy_from_cpu handles."""
        feed_names = self._meta["feed_names"]
        if inputs is not None:
            vals = [jnp.asarray(x) for x in inputs]
        else:
            missing = [n for n in feed_names if n not in self._inputs]
            if missing:
                raise RuntimeError(f"inputs not staged: {missing}")
            vals = [self._inputs[n] for n in feed_names]
        outs = self._exported.call(*vals)
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        self._outputs = dict(zip(self._meta["fetch_names"], outs))
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def clear_intermediate_tensor(self):
        self._inputs.clear()
        self._outputs.clear()


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
