"""Compact store-backed RPC for the cross-process serving fleet.

The parent supervisor and each replica subprocess already share exactly one
piece of infrastructure: the :class:`~..distributed.store.TCPStore` (the
same rendezvous substrate the launcher, the elastic membership layer, and
the fleet heartbeats ride). This module turns it into a pair of ordered,
single-writer message channels per replica::

    procfleet/<ns>/<rid>/in    parent -> child   submit / cancel / drain
    procfleet/<ns>/<rid>/out   child -> parent   tick / chunk / finished

A :class:`Channel` is an append-only log: the (single) writer serializes
each message as JSON under ``<prefix>/m/<seq>`` and THEN bumps the
``<prefix>/n`` counter — so a reader that observes ``n == k`` can fetch
messages ``1..k`` without racing a half-published entry, and a writer that
dies mid-send (SIGKILL, segfault) leaves at worst an orphaned key the
counter never acknowledged. Reads are destructive (``delete_key`` after
fetch) so a long-lived serving store doesn't accumulate the whole token
history. Ordering is total per channel: sequence numbers are assigned by
the writer, drained in order by the reader — the property the per-token
streaming ledger's chunk sequence numbers build on.

Heartbeats deliberately do NOT ride the message log (a beat per tick would
dominate the store traffic): each replica overwrites one well-known key,
``procfleet/<ns>/<rid>/hb``, with a monotonic beat counter plus its local
``infer.*`` counters (compiles / AOT hits — per-process state the parent
cannot see any other way) and the parent's stale-beat sweep watches the
counter for motion, not the wall clock, so cross-host clock skew cannot
fake a death.

Every envelope carries the fleet ``trace_id`` (PR 14): the child attaches
it to its scheduler submission, so one trace spans parent placement, child
prefill/decode spans, requeue, and delivery across process boundaries.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

__all__ = ["Channel", "Heartbeat", "channel_prefix", "hb_key"]


def channel_prefix(ns: str, rid: int, direction: str) -> str:
    """The store key prefix for one replica-channel direction ('in' is
    parent->child, 'out' is child->parent)."""
    return f"procfleet/{ns}/{rid}/{direction}"


def hb_key(ns: str, rid: int) -> str:
    return f"procfleet/{ns}/{rid}/hb"


class Channel:
    """One direction of ordered message flow over a TCPStore.

    Exactly one process may :meth:`send` and exactly one may :meth:`recv`
    on a given prefix — sequence numbers are writer-local, which is what
    makes the set-then-bump publication protocol race-free without a
    store-side transaction."""

    def __init__(self, store, prefix: str):
        self.store = store
        self.prefix = prefix
        self._sent = 0   # writer: last sequence number published
        self._read = 0   # reader: last sequence number consumed

    # ------------------------------------------------------------- writer
    def send(self, kind: str, **payload: Any) -> int:
        """Publish one message; returns its sequence number. The message
        body lands under ``m/<seq>`` BEFORE the ``n`` counter acknowledges
        it, so readers never observe a torn write."""
        self._sent += 1
        msg = {"kind": kind, "seq": self._sent}
        msg.update(payload)
        self.store.set(f"{self.prefix}/m/{self._sent}", json.dumps(msg))
        self.store.add(f"{self.prefix}/n", 1)
        return self._sent

    # ------------------------------------------------------------- reader
    def recv(self, timeout: float = 2.0) -> List[Dict[str, Any]]:
        """Drain every message published since the last call, in order.
        Non-blocking when nothing is pending (one counter read); the
        ``timeout`` only bounds the body fetch of an acknowledged message
        (which the writer has already set — it arrives immediately)."""
        n = int(self.store.add(f"{self.prefix}/n", 0))
        out: List[Dict[str, Any]] = []
        while self._read < n:
            seq = self._read + 1
            raw = self.store.get(f"{self.prefix}/m/{seq}", timeout=timeout)
            out.append(json.loads(raw if isinstance(raw, str) else raw.decode()))  # noqa: PTA104 (host-side serving loop, never traced)
            try:
                self.store.delete_key(f"{self.prefix}/m/{seq}")
            except OSError:
                pass  # GC is best-effort; the counter already moved on
            self._read = seq  # noqa: PTA104 (host-side, never traced)
        return out


class Heartbeat:
    """The one-key beat a replica subprocess publishes and the parent
    sweeps. ``beat()`` overwrites; ``read()`` parses; staleness is judged
    by the PARENT's monotonic clock against the last time the beat counter
    moved (see ProcReplica), never by comparing wall clocks."""

    def __init__(self, store, ns: str, rid: int):
        self.store = store
        self.key = hb_key(ns, rid)
        self._n = 0

    def beat(self, **extra: Any) -> None:
        self._n += 1
        doc = {"n": self._n, "ts": time.time()}
        doc.update(extra)
        self.store.set(self.key, json.dumps(doc))

    def read(self, timeout: float = 0.05) -> Optional[Dict[str, Any]]:
        """The latest published beat, or None when the replica has not
        beaten yet (still importing/booting)."""
        try:
            raw = self.store.get(self.key, timeout=timeout)
        except (TimeoutError, OSError):
            return None
        try:
            return json.loads(raw if isinstance(raw, str) else raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None
