"""Compact store-backed RPC for the cross-process serving fleet.

The parent supervisor and each replica subprocess already share exactly one
piece of infrastructure: the :class:`~..distributed.store.TCPStore` (the
same rendezvous substrate the launcher, the elastic membership layer, and
the fleet heartbeats ride). This module turns it into a pair of ordered,
single-writer message channels per replica::

    procfleet/<ns>/<rid>/in    parent -> child   submit / cancel / drain
    procfleet/<ns>/<rid>/out   child -> parent   tick / chunk / finished

A :class:`Channel` is an append-only log: the (single) writer serializes
each message as JSON under ``<prefix>/m/<seq>`` and THEN bumps the
``<prefix>/n`` counter — so a reader that observes ``n == k`` can fetch
messages ``1..k`` without racing a half-published entry, and a writer that
dies mid-send (SIGKILL, segfault) leaves at worst an orphaned key the
counter never acknowledged. Reads are destructive (``delete_key`` after
fetch) so a long-lived serving store doesn't accumulate the whole token
history. Ordering is total per channel: sequence numbers are assigned by
the writer, drained in order by the reader — the property the per-token
streaming ledger's chunk sequence numbers build on. A drain interrupted by
a store failure returns the messages it already consumed (a **partial
drain**) instead of discarding them with the exception: those bodies are
deleted and the cursor has moved, so dropping them would lose acknowledged
messages; the failing sequence number stays unconsumed and the next call
retries it.

:class:`SocketChannel` (PR 20) is the **hot-path fast lane** over the same
contract: one full-duplex length-prefixed-frame TCP socket per replica
carries submit/chunk/tick traffic directly between parent and child (the
store's ~3x polling overhead drops to a socket write), while the TCPStore
stays authoritative for membership, heartbeats, and boot. Sequence numbers
are still writer-assigned and delivery is still in-order and exactly-once:
the writer retains every socket-sent message until the reader acknowledges
it (acks ride the same socket), and ANY socket error — connect failure,
reset, a chaos ``FLAGS_chaos_socket_drop_at`` kill — degrades the channel
back to the store transport mid-stream by republishing the unacknowledged
window under the same sequence numbers (set-then-bump, as ever). The
reader dedups by cursor, so the fallback can replay generously without
ever delivering a message twice, dropping one, or reordering.

Heartbeats deliberately do NOT ride the message log (a beat per tick would
dominate the store traffic): each replica overwrites one well-known key,
``procfleet/<ns>/<rid>/hb``, with a monotonic beat counter plus its local
``infer.*`` counters (compiles / AOT hits — per-process state the parent
cannot see any other way) and the parent's stale-beat sweep watches the
counter for motion, not the wall clock, so cross-host clock skew cannot
fake a death.

Every envelope carries the fleet ``trace_id`` (PR 14): the child attaches
it to its scheduler submission, so one trace spans parent placement, child
prefill/decode spans, requeue, and delivery across process boundaries.
"""
from __future__ import annotations

import json
import select
import socket
import time
from typing import Any, Dict, List, Optional

__all__ = ["Channel", "SocketChannel", "SocketConn", "SocketListener",
           "Heartbeat", "channel_prefix", "hb_key", "sock_key",
           "connect_socket"]


def channel_prefix(ns: str, rid: int, direction: str) -> str:
    """The store key prefix for one replica-channel direction ('in' is
    parent->child, 'out' is child->parent)."""
    return f"procfleet/{ns}/{rid}/{direction}"


def hb_key(ns: str, rid: int) -> str:
    return f"procfleet/{ns}/{rid}/hb"


def sock_key(ns: str, rid: int) -> str:
    """Where a replica advertises its fast-path socket endpoint."""
    return f"procfleet/{ns}/{rid}/sock"


class Channel:
    """One direction of ordered message flow over a TCPStore.

    Exactly one process may :meth:`send` and exactly one may :meth:`recv`
    on a given prefix — sequence numbers are writer-local, which is what
    makes the set-then-bump publication protocol race-free without a
    store-side transaction."""

    def __init__(self, store, prefix: str):
        self.store = store
        self.prefix = prefix
        self._sent = 0   # writer: last sequence number published
        self._read = 0   # reader: last sequence number consumed

    # ------------------------------------------------------------- writer
    def send(self, kind: str, **payload: Any) -> int:
        """Publish one message; returns its sequence number. The message
        body lands under ``m/<seq>`` BEFORE the ``n`` counter acknowledges
        it, so readers never observe a torn write."""
        self._sent += 1
        msg = {"kind": kind, "seq": self._sent}
        msg.update(payload)
        self.store.set(f"{self.prefix}/m/{self._sent}", json.dumps(msg))
        self.store.add(f"{self.prefix}/n", 1)
        return self._sent

    # ------------------------------------------------------------- reader
    def recv(self, timeout: float = 2.0) -> List[Dict[str, Any]]:
        """Drain every message published since the last call, in order.
        Non-blocking when nothing is pending (one counter read); the
        ``timeout`` only bounds the body fetch of an acknowledged message
        (which the writer has already set — it arrives immediately).

        A store failure partway through the drain returns the messages
        already consumed (their bodies are deleted and the cursor moved —
        discarding them would silently lose acknowledged messages); the
        failing sequence number is NOT consumed, so the next call retries
        it, and a drain that fails before consuming anything raises."""
        n = int(self.store.add(f"{self.prefix}/n", 0))
        out: List[Dict[str, Any]] = []
        while self._read < n:
            seq = self._read + 1
            try:
                raw = self.store.get(f"{self.prefix}/m/{seq}", timeout=timeout)
                msg = json.loads(raw if isinstance(raw, str) else raw.decode())  # noqa: PTA104 (host-side serving loop, never traced)
            except (TimeoutError, OSError, ValueError):
                if out:
                    from ..observability.metrics import counter_inc

                    counter_inc("rpc.partial_drains")
                    return out  # partial drain: consumed messages survive  # noqa: PTA101 (host-side serving transport, never traced)
                raise
            out.append(msg)  # noqa: PTA104 (host-side serving transport, never traced)
            try:
                self.store.delete_key(f"{self.prefix}/m/{seq}")
            except OSError:
                pass  # GC is best-effort; the counter already moved on
            self._read = seq  # noqa: PTA104 (host-side, never traced)
        return out


# =====================================================================
# socket fast path
# =====================================================================

class SocketConn:
    """One full-duplex framed TCP connection between the parent and one
    replica child, multiplexing both hot channels ('in': parent->child,
    'out': child->parent) plus piggybacked acknowledgements.

    Frames are 4-byte big-endian length + JSON:
    ``{"ch": name, "msg": {...}}`` carries one channel message,
    ``{"ackch": name, "ack": N}`` acknowledges in-order delivery through
    sequence N on channel ``name``. Any socket error (send failure, EOF,
    torn frame) marks the connection dead — callers degrade to the store
    transport; there is no reconnect (the store path is always correct,
    just slower)."""

    def __init__(self, sock: socket.socket, timeout: float = 5.0):
        sock.settimeout(timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.sock = sock
        self.alive = True
        self.death_reason: Optional[str] = None
        self._rbuf = b""
        self.inbox: Dict[str, List[dict]] = {}   # channel -> received msgs
        self.acks: Dict[str, int] = {}           # channel -> peer's ack

    def send_frame(self, doc: dict) -> bool:
        """Write one frame; False (and the conn is dead) on any error."""
        if not self.alive:
            return False
        from ..testing import chaos

        delay = chaos.net_delay_ms()
        if delay > 0:
            time.sleep(delay / 1e3)
        data = json.dumps(doc).encode()
        try:
            self.sock.sendall(len(data).to_bytes(4, "big") + data)
            return True
        except (OSError, ValueError):
            self.kill("send error")
            return False

    def poll(self) -> None:
        """Drain every readable byte (never blocks) and parse complete
        frames into :attr:`inbox` / :attr:`acks`."""
        if not self.alive:
            return
        try:
            while True:
                r, _, _ = select.select([self.sock], [], [], 0)
                if not r:
                    break
                data = self.sock.recv(1 << 16)
                if not data:
                    self.kill("peer closed")
                    break
                self._rbuf += data  # noqa: PTA104 (host-side transport, never traced)
        except (OSError, ValueError):
            self.kill("recv error")
        while len(self._rbuf) >= 4:
            ln = int.from_bytes(self._rbuf[:4], "big")
            if len(self._rbuf) < 4 + ln:
                break
            body, self._rbuf = self._rbuf[4:4 + ln], self._rbuf[4 + ln:]  # noqa: PTA104 (host-side serving transport, never traced)
            try:
                doc = json.loads(body)
            except ValueError:
                self.kill("torn frame")
                return  # noqa: PTA101 (host-side serving transport, never traced)
            if "msg" in doc:
                self.inbox.setdefault(doc.get("ch"), []).append(doc["msg"])  # noqa: PTA104, PTA305 (host-side, never traced; one list per channel, drained by take())
            if doc.get("ack") is not None:
                ch = doc.get("ackch", doc.get("ch"))
                self.acks[ch] = max(self.acks.get(ch, 0), int(doc["ack"]))  # noqa: PTA104, PTA305 (host-side, never traced; one cursor per channel, overwritten)

    def take(self, channel: str) -> List[dict]:
        msgs = self.inbox.get(channel) or []
        self.inbox[channel] = []
        return msgs

    def kill(self, reason: str = "") -> None:
        if self.alive:
            self.alive = False  # noqa: PTA104 (host-side serving transport, never traced)
            self.death_reason = reason or "killed"  # noqa: PTA104 (host-side serving transport, never traced)
        try:
            self.sock.close()
        except OSError:
            pass

    close = kill


class SocketListener:
    """The child side's accept socket: bind an ephemeral port, advertise
    ``host:port`` (via the store's :func:`sock_key`), accept the parent's
    one connection non-blockingly from the serving loop."""

    def __init__(self, advertise_host: str = "127.0.0.1"):
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("", 0))
        self.sock.listen(1)
        self.sock.setblocking(False)
        self.port = self.sock.getsockname()[1]
        self.address = f"{advertise_host}:{self.port}"

    def try_accept(self) -> Optional[SocketConn]:
        try:
            s, _addr = self.sock.accept()
        except (BlockingIOError, InterruptedError):
            return None
        except OSError:
            return None
        from ..observability.metrics import counter_inc

        counter_inc("rpc.socket_connects")
        return SocketConn(s)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def connect_socket(store, ns: str, rid: int,
                   timeout: float = 0.25) -> Optional[SocketConn]:
    """Parent-side dial of replica ``rid``'s advertised fast-path socket.
    None when the replica never advertised one (socket fast path disabled,
    or an old child) or the dial fails — callers simply stay on the store
    transport."""
    try:
        raw = store.get(sock_key(ns, rid), timeout=timeout)
    except (TimeoutError, OSError):
        return None
    addr = raw if isinstance(raw, str) else raw.decode()
    host, _, port = addr.rpartition(":")
    try:
        s = socket.create_connection((host, int(port)), timeout=2.0)
    except (OSError, ValueError):
        return None
    from ..observability.metrics import counter_inc

    counter_inc("rpc.socket_connects")
    return SocketConn(s)


class SocketChannel(Channel):
    """A :class:`Channel` with a direct-socket fast lane and automatic,
    loss-free degradation back to the store transport.

    Both ends share one :class:`SocketConn` per replica (held in a mutable
    one-slot ``conn_box`` so the serving loop can install it after boot);
    the channel ``name`` ('in'/'out') tags frames on the shared wire.

    **Writer protocol**: every message gets the next writer-local seq and
    is retained in an unacked window; while the socket is alive it travels
    as one frame (no store ops at all). On any socket failure — or when no
    socket ever connected — :meth:`_flush_to_store` republishes every
    retained message the peer has not acknowledged under its ORIGINAL
    ``m/<seq>`` key and advances the ``n`` counter to the latest seq
    (set-then-bump, the same ordering contract). Acked messages are
    dropped from the window (the reader's cursor passed them; the counter
    may skip their bodies safely).

    **Reader protocol**: socket frames land in a pending map and are
    delivered strictly in cursor order; the store counter is consulted
    when the socket is dead, when a gap suggests store-published messages,
    and periodically (every :data:`STORE_CHECK_EVERY` drains) as a
    half-open-socket safety net — so the steady-state hot path costs zero
    store round-trips. Delivery acks ride back on the socket, bounding the
    writer's window. Cursor dedup makes fallback replays harmless: a
    message can arrive on both transports and is delivered exactly once,
    in order."""

    STORE_CHECK_EVERY = 32

    def __init__(self, store, prefix: str, name: str, conn_box: list,
                 rid: int = 0):
        super().__init__(store, prefix)
        self.name = name
        self.rid = int(rid)
        self._conn_box = conn_box
        self._unacked: Dict[int, dict] = {}  # writer: replay window
        self._pending: Dict[int, dict] = {}  # reader: out-of-order arrivals
        self._store_n = 0      # counter value this writer has driven
        self._calls = 0
        self.socket_msgs = 0   # sent via socket
        self.store_msgs = 0    # published to the store
        self.fallbacks = 0     # socket->store degradations observed

    def _conn(self) -> Optional[SocketConn]:
        return self._conn_box[0] if self._conn_box else None

    def backlog(self) -> int:
        """Writer-side transport lag: messages sent but not yet
        acknowledged by the peer (0 in pure store mode — the store IS the
        ack). The ingress reads this as a backpressure watermark."""
        return len(self._unacked)

    # ------------------------------------------------------------- writer
    def send(self, kind: str, **payload: Any) -> int:
        from ..observability.metrics import counter_inc
        from ..testing import chaos

        self._sent += 1
        msg = {"kind": kind, "seq": self._sent}
        msg.update(payload)
        self._unacked[self._sent] = msg
        conn = self._conn()
        if conn is not None and conn.alive:
            if chaos.socket_drop_due(self.rid, self.socket_msgs + 1):
                conn.kill("chaos: socket drop")
                self.fallbacks += 1  # noqa: PTA104 (host-side serving transport, never traced)
                counter_inc("rpc.socket_fallbacks")
            else:
                ack = conn.acks.get(self.name, 0)
                for seq in [s for s in self._unacked if s <= ack]:
                    del self._unacked[seq]
                if conn.send_frame({"ch": self.name, "msg": msg}):
                    self.socket_msgs += 1  # noqa: PTA104 (host-side serving transport, never traced)
                    counter_inc("rpc.socket_msgs")
                    return self._sent
                self.fallbacks += 1  # noqa: PTA104 (host-side serving transport, never traced)
                counter_inc("rpc.socket_fallbacks")
        self._flush_to_store()
        return self._sent

    def _flush_to_store(self) -> None:
        """Republish the unacknowledged window under the original seqs and
        bump the counter to the latest — the loss-free fallback seam. Safe
        to call repeatedly; already-published seqs are skipped and the
        counter only ever moves forward."""
        from ..observability.metrics import counter_inc

        conn = self._conn()
        acked = conn.acks.get(self.name, 0) if conn is not None else 0
        for seq in sorted(self._unacked):
            if seq <= acked:
                continue  # delivered: the reader's cursor already passed it
            msg = self._unacked[seq]
            self.store.set(f"{self.prefix}/m/{seq}", json.dumps(msg))
            self.store_msgs += 1  # noqa: PTA104 (host-side serving transport, never traced)
            counter_inc("rpc.store_msgs")
        if self._sent > self._store_n:
            self.store.add(f"{self.prefix}/n", self._sent - self._store_n)  # noqa: PTA104 (host-side serving transport, never traced)
            self._store_n = self._sent  # noqa: PTA104 (host-side transport)
        self._unacked.clear()  # everything <= _sent is published or acked

    # ------------------------------------------------------------- reader
    def recv(self, timeout: float = 2.0) -> List[Dict[str, Any]]:
        self._calls += 1
        conn = self._conn()
        if conn is not None and conn.alive:
            conn.poll()
            for m in conn.take(self.name):
                seq = int(m.get("seq", 0))
                if seq > self._read:
                    self._pending[seq] = m  # noqa: PTA104 (host-side transport)
        out: List[Dict[str, Any]] = []
        self._deliver(out)
        conn = self._conn()  # poll may have killed it
        socket_ok = conn is not None and conn.alive
        if (not socket_ok or self._pending
                or self._calls % self.STORE_CHECK_EVERY == 0):
            self._drain_store(out, timeout)
        if out and socket_ok:
            conn.send_frame({"ackch": self.name, "ack": self._read})
        return out

    def _deliver(self, out: List[dict]) -> None:
        while self._read + 1 in self._pending:
            self._read += 1  # noqa: PTA104 (host-side serving transport, never traced)
            out.append(self._pending.pop(self._read))  # noqa: PTA104 (host-side serving transport, never traced)

    def _drain_store(self, out: List[dict], timeout: float) -> None:
        """Fetch store-published messages past the cursor, interleaving
        socket arrivals (pending entries win: their body fetch is free and
        the store copy of a socket-delivered seq is just the fallback
        replay). Same partial-drain discipline as :class:`Channel`."""
        n = int(self.store.add(f"{self.prefix}/n", 0))
        while self._read < n:
            seq = self._read + 1
            m = self._pending.pop(seq, None)
            if m is None:
                try:
                    raw = self.store.get(f"{self.prefix}/m/{seq}", timeout=timeout)
                    m = json.loads(raw if isinstance(raw, str) else raw.decode())  # noqa: PTA104 (host-side transport, never traced)
                except (TimeoutError, OSError, ValueError):
                    if out:
                        from ..observability.metrics import counter_inc

                        counter_inc("rpc.partial_drains")
                        return  # partial drain: keep what was consumed  # noqa: PTA101 (host-side serving transport, never traced)
                    raise
            out.append(m)  # noqa: PTA104 (host-side serving transport, never traced)
            try:
                self.store.delete_key(f"{self.prefix}/m/{seq}")
            except OSError:
                pass
            self._read = seq  # noqa: PTA104 (host-side transport, never traced)
            self._deliver(out)  # socket arrivals past the store counter
        self._deliver(out)


class Heartbeat:
    """The one-key beat a replica subprocess publishes and the parent
    sweeps. ``beat()`` overwrites; ``read()`` parses; staleness is judged
    by the PARENT's monotonic clock against the last time the beat counter
    moved (see ProcReplica), never by comparing wall clocks."""

    def __init__(self, store, ns: str, rid: int):
        self.store = store
        self.key = hb_key(ns, rid)
        self._n = 0

    def beat(self, **extra: Any) -> None:
        self._n += 1
        doc = {"n": self._n, "ts": time.time()}
        doc.update(extra)
        self.store.set(self.key, json.dumps(doc))

    def read(self, timeout: float = 0.05) -> Optional[Dict[str, Any]]:
        """The latest published beat, or None when the replica has not
        beaten yet (still importing/booting)."""
        try:
            raw = self.store.get(self.key, timeout=timeout)
        except (TimeoutError, OSError):
            return None
        try:
            return json.loads(raw if isinstance(raw, str) else raw.decode())
        except (ValueError, UnicodeDecodeError):
            return None
