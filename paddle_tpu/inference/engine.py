"""Static KV-cache decode engine: the serving hot path.

Replaces the growing-concat ``MultiHeadAttention.Cache`` decode (a new
shape — and under jit a new compiled program — every token) with a
preallocated device-resident cache updated in place at traced position
indices. Exactly TWO compiled programs serve an entire request stream:

- **prefill** — one compile per prompt-length bucket: runs the prompt
  through the trunk on a fresh ``[L, 1, H, P, dh]`` cache segment, inserts
  it into the engine's big ``[L, B, H, S, dh]`` cache at a batch *slot*
  index, and samples the first token;
- **decode step** — ONE compile total: advances every occupied slot one
  token with per-slot position indices (slots at different depths share the
  program), slot-masked sampling, and in-place K/V writes.

Both programs donate the cache (and slot-state) buffers — the XLA executable
updates them in place, so cache memory stays flat for the life of the engine
(the PR-3 donation idiom from ``jit.TrainStep``/the static Executor, applied
to serving). Compiles run through the observability AOT ``lower().compile()``
path, so ``explain()`` answers cost/memory questions and the
``infer.compiles`` counter lets tests pin "decode of N tokens compiles
exactly 2 programs".

Parity: the reference serves GPT decode through
``fused_multi_transformer_op.cu`` driven by AnalysisPredictor; here the
fused decoder is the compiled step program and the "predictor" is the
:class:`~paddle_tpu.inference.scheduler.ContinuousBatchingScheduler` on top.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DecodeEngine", "default_buckets"]


def default_buckets(max_seq: int, start: int = 16) -> Tuple[int, ...]:
    """Power-of-two prompt-padding buckets up to ``max_seq``: prompts pad to
    the smallest bucket that fits, so prefill compiles once per bucket
    instead of once per prompt length."""
    out: List[int] = []
    b = start
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(sorted(set(out)))


def _dequant(entry, dt):
    """A params-pack entry is either a plain array or an int8 payload
    ``{"q", "s"}``; dequantize the latter to ``dt`` (XLA folds the multiply
    into the consuming matmul — the QuantizedLinear idiom on raw stacked
    weights)."""
    if isinstance(entry, dict):
        return (entry["q"].astype(jnp.float32) * entry["s"]).astype(dt)
    return entry


class DecodeEngine:
    """Slot-based autoregressive decode over a static KV cache.

    ``model`` is a :class:`~paddle_tpu.models.gpt.GPTForPretraining` with the
    stacked trunk. ``max_batch_slots`` fixes the decode batch width B: each
    slot holds one in-flight request, and requests are admitted into free
    slots mid-stream (continuous batching) — admission never recompiles.

    ``int8=True`` quantizes the trunk matmul weights (qkv/out/ffn1/ffn2)
    to int8 with per-layer × per-output-channel abs_max scales through
    :mod:`paddle_tpu.quantization`; the compiled programs carry int8
    constants and dequantize into the matmuls.

    Sampling config (``do_sample``/``temperature``/``top_k``/``top_p``) is
    compiled into the programs; per-request randomness comes from each
    request's own ``seed`` folded with its absolute position, so a request's
    tokens never depend on which slot it runs in or on its batch neighbours.
    """

    def __init__(self, model, max_batch_slots: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 int8: bool = False, donate: bool = True):
        from ..models.gpt import GPTBlockStack

        if not isinstance(model.gpt.layers, GPTBlockStack):
            raise NotImplementedError("DecodeEngine requires the stacked trunk (GPTConfig(stacked=True))")
        cfg = model.gpt.cfg
        S = int(max_seq_len) if max_seq_len is not None else int(cfg.max_seq_len)
        if S > cfg.max_seq_len:
            raise ValueError(f"max_seq_len {S} exceeds the model's positional table {cfg.max_seq_len}")
        self.cfg = cfg
        self.max_seq_len = S
        self.max_batch_slots = B = int(max_batch_slots)
        self.buckets = tuple(sorted(int(b) for b in prefill_buckets)) if prefill_buckets else default_buckets(S)
        if any(b > S for b in self.buckets):
            raise ValueError(f"prefill bucket larger than max_seq_len {S}: {self.buckets}")
        self._sample = (bool(do_sample), float(temperature), int(top_k), float(top_p))
        self.int8 = bool(int8)
        self._donate = bool(donate)

        stacked, wte, wpe, fnw, fnb = model._decode_params()
        params, self._idx = stacked
        self._stack_dts = tuple(w.dtype for w in params)  # dequant targets
        if int8:
            from .. import quantization as Q

            order = model.gpt.layers._order
            quant = {"qkv_w", "out_w", "ffn1_w", "ffn2_w"}
            packed = []
            for name, w in zip(order, params):
                if name in quant:
                    # per-layer × per-output-channel abs_max scales on the
                    # [L, in, out]-stacked trunk weight (channel_wise_abs_max
                    # over the stack) — int8 constants land in the compiled
                    # programs, dequant folds into the matmul
                    q, s = Q.quant_abs_max(np.asarray(w), channel_axis=(0, 2))
                    packed.append({"q": jnp.asarray(q), "s": jnp.asarray(s)})
                else:
                    packed.append(w)
            params = tuple(packed)
        self._params = {"stack": params, "wte": wte, "wpe": wpe, "fnw": fnw, "fnb": fnb}

        L = cfg.num_layers
        H = cfg.num_heads
        dh = cfg.hidden_size // cfg.num_heads
        dt = wte.dtype
        self._shape = (L, B, H, S, dh)
        self._ck = jnp.zeros((L, B, H, S, dh), dt)
        self._cv = jnp.zeros((L, B, H, S, dh), dt)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._tok = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        # host mirrors / per-slot request metadata (tiny, resent per dispatch)
        self._active_np = np.zeros((B,), bool)
        self._occupied = np.zeros((B,), bool)
        self._eos = np.full((B,), -1, np.int32)
        self._limit = np.zeros((B,), np.int32)
        self._seed = np.zeros((B,), np.int32)

        self._build()
        self._compiled: Dict[tuple, Any] = {}
        self._specializations: List[dict] = []

    # ------------------------------------------------------------ programs
    def _build(self):
        from ..models.gpt import _cache_forward, _select_token, _select_token_rows, _slot_decode_forward

        cfg = self.cfg
        num_heads = cfg.num_heads
        L = cfg.num_layers
        H = num_heads
        dh = cfg.hidden_size // num_heads
        do_sample, temperature, top_k, top_p = self._sample
        idx = self._idx

        dts = self._stack_dts

        def unpack(p):
            return ((tuple(_dequant(e, dt) for e, dt in zip(p["stack"], dts)), idx),
                    p["wte"], p["wpe"], p["fnw"], p["fnb"])

        def prefill_fn(p, ck, cv, pos, tok, active, ids, length, slot, eos, limit, seed):
            stacked, wte, wpe, fnw, fnb = unpack(p)
            P = ids.shape[1]
            sk = jnp.zeros((L, 1, H, P, dh), wte.dtype)
            sv = jnp.zeros((L, 1, H, P, dh), wte.dtype)
            logits, sk, sv = _cache_forward(stacked, wte, wpe, fnw, fnb, ids, sk, sv,
                                            jnp.int32(0), num_heads=num_heads)
            ck = jax.lax.dynamic_update_slice(ck, sk, (0, slot, 0, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, sv, (0, slot, 0, 0, 0))
            last = jax.lax.dynamic_slice(logits, (0, length - 1, 0), (1, 1, logits.shape[2]))[:, 0]
            key = jax.random.fold_in(jax.random.key(seed), length - 1)
            first = _select_token(last.astype(jnp.float32), key, do_sample, temperature, top_k, top_p)[0]
            done = (eos >= 0) & (first == eos)
            more = (~done) & (length + 1 < limit)
            dus = jax.lax.dynamic_update_slice
            pos = dus(pos, length[None], (slot,))
            tok = dus(tok, first[None], (slot,))
            active = dus(active, more[None], (slot,))
            return ck, cv, pos, tok, active, first, more

        def decode_fn(p, ck, cv, pos, tok, active, eos_v, limit_v, seed_v):
            stacked, wte, wpe, fnw, fnb = unpack(p)
            logits, ck, cv = _slot_decode_forward(stacked, wte, wpe, fnw, fnb, tok, ck, cv,
                                                  pos, num_heads=num_heads)
            keys = jax.vmap(lambda s, q: jax.random.fold_in(jax.random.key(s), q))(seed_v, pos)
            nxt = _select_token_rows(logits.astype(jnp.float32), keys, do_sample,
                                     temperature, top_k, top_p)
            nxt = jnp.where(active, nxt, tok)  # slot-masked: free slots hold
            hit_eos = (eos_v >= 0) & (nxt == eos_v)
            new_pos = pos + active.astype(jnp.int32)
            new_active = active & ~hit_eos & (new_pos + 1 < limit_v)
            return ck, cv, new_pos, nxt, new_active

        donate = (1, 2, 3, 4, 5) if self._donate else ()
        self._prefill_jit = jax.jit(prefill_fn, donate_argnums=donate)
        self._decode_jit = jax.jit(decode_fn, donate_argnums=donate)

    def _dispatch(self, which: str, jitfn, args):
        """Run one dispatch, AOT-compiling on a new (kind, shape) signature
        so the XLA Compiled handle is retained for ``explain()`` and the
        compile is counted/logged — the TrainStep._dispatch idiom."""
        sig = (which,) + tuple(
            (tuple(l.shape), str(l.dtype)) for l in jax.tree_util.tree_leaves(args))
        entry = self._compiled.get(sig)
        if entry is None:
            from ..observability import introspect as _introspect
            from ..observability import runlog as _runlog
            from ..observability import span as _span
            from ..profiler import counter_inc

            with _span("infer.compile"):
                compiled, info = _introspect.aot_compile(jitfn, args)
            entry = compiled if compiled is not None else jitfn
            self._compiled[sig] = entry
            counter_inc("infer.compiles")
            info["label"] = which if which == "decode" else f"{which}/P{args[6].shape[1]}"
            info["kind"] = which
            self._specializations.append(info)
            _runlog.emit("compile", component="infer", label=info["label"],
                         seconds=info.get("compile_seconds"),
                         flops=info.get("flops"),
                         bytes_accessed=info.get("bytes_accessed"),
                         peak_bytes=info.get("peak_bytes"))
        try:
            return entry(*args)
        except (TypeError, ValueError):
            if entry is jitfn:
                raise
            self._compiled[sig] = jitfn  # AOT aval drift: jit path forever
            return jitfn(*args)

    # ------------------------------------------------------------ slot API
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(f"prompt of {prompt_len} tokens exceeds the largest "
                         f"prefill bucket {self.buckets[-1]}")

    def free_slots(self) -> List[int]:
        return [i for i in range(self.max_batch_slots) if not self._occupied[i]]

    def prefill(self, prompt, slot: int, max_new_tokens: int, eos_token_id: Optional[int] = None,
                seed: int = 0) -> Tuple[int, bool]:
        """Admit one prompt into ``slot``: run the bucketed prefill program,
        write its KV into the slot's cache lanes, sample the first token.
        Returns ``(first_token, more)`` — ``more`` False means the request
        finished at its first token (eos or max_new_tokens == 1)."""
        from ..observability import span as _span
        from ..profiler import counter_inc

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(prompt.shape[0])
        if n < 1:
            raise ValueError("empty prompt")
        if self._occupied[slot]:
            raise ValueError(f"slot {slot} is occupied; free it first")
        if n + int(max_new_tokens) > self.max_seq_len:
            raise ValueError(f"prompt {n} + max_new_tokens {max_new_tokens} "
                             f"exceeds max_seq_len {self.max_seq_len}")
        P = self.bucket_for(n)
        ids = np.zeros((1, P), np.int32)
        ids[0, :n] = prompt
        eos = -1 if eos_token_id is None else int(eos_token_id)
        limit = n + int(max_new_tokens)
        with _span("infer.prefill"):
            out = self._dispatch(
                "prefill", self._prefill_jit,
                (self._params, self._ck, self._cv, self._pos, self._tok, self._active,
                 jnp.asarray(ids), jnp.int32(n), jnp.int32(slot), jnp.int32(eos),
                 jnp.int32(limit), jnp.int32(seed)))
        self._ck, self._cv, self._pos, self._tok, self._active, first, more = out
        more = bool(more)
        self._occupied[slot] = True
        self._active_np[slot] = more
        self._eos[slot] = eos
        self._limit[slot] = limit
        self._seed[slot] = int(seed)
        counter_inc("infer.prefill_dispatches")
        counter_inc("infer.tokens")
        return int(first), more

    def decode_step(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One token for every active slot in ONE dispatch. Returns
        ``(tokens[B], emitted[B], active[B])`` where ``emitted`` marks slots
        that produced a real token this step (their pre-step active mask)
        and ``active`` is the post-step mask (False = request finished)."""
        from ..observability import span as _span
        from ..profiler import counter_inc

        emitted = self._active_np.copy()
        with _span("infer.decode_step"):
            out = self._dispatch(
                "decode", self._decode_jit,
                (self._params, self._ck, self._cv, self._pos, self._tok, self._active,
                 jnp.asarray(self._eos), jnp.asarray(self._limit), jnp.asarray(self._seed)))
        self._ck, self._cv, self._pos, self._tok, self._active = out
        toks = np.asarray(self._tok)
        self._active_np = np.array(self._active)  # writable host mirror
        counter_inc("infer.decode_dispatches")
        counter_inc("infer.tokens", int(emitted.sum()))
        return toks, emitted, self._active_np.copy()

    def free_slot(self, slot: int) -> None:
        """Release a slot for the next admission (cancels it if still live)."""
        if self._active_np[slot]:
            self._active = self._active.at[slot].set(False)
            self._active_np[slot] = False
        self._occupied[slot] = False

    def reset(self) -> None:
        """Drop every in-flight request and zero the slot state (the cache
        keeps its buffers — stale K/V is always overwritten before it can be
        attended)."""
        B = self.max_batch_slots
        self._pos = jnp.zeros((B,), jnp.int32)
        self._tok = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        self._active_np[:] = False
        self._occupied[:] = False
        self._eos[:] = -1
        self._limit[:] = 0
        self._seed[:] = 0

    # ------------------------------------------------------------- helpers
    def generate(self, ids, max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
                 seed: int = 0) -> np.ndarray:
        """Batch generate through the slot machinery (parity helper + the
        bench decode path): each row takes one slot, prefill once per row,
        then decode steps until every row finishes. Returns
        ``[b, s0 + max_new_tokens]`` int32 (rows that hit eos pad with it) —
        same contract as ``GPTForPretraining.generate``."""
        ids = np.asarray(ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        b, s0 = ids.shape
        if b > self.max_batch_slots:
            raise ValueError(f"batch {b} exceeds max_batch_slots {self.max_batch_slots}")
        self.reset()
        rows = [[] for _ in range(b)]
        for i in range(b):
            tok, _more = self.prefill(ids[i], slot=i, max_new_tokens=max_new_tokens,
                                      eos_token_id=eos_token_id, seed=seed)
            rows[i].append(tok)
        while self._active_np.any():
            toks, emitted, _ = self.decode_step()
            for i in range(b):
                if emitted[i]:
                    rows[i].append(int(toks[i]))
        for i in range(b):
            self.free_slot(i)
        out = np.zeros((b, s0 + int(max_new_tokens)), np.int32)
        out[:, :s0] = ids
        for i, r in enumerate(rows):
            pad = r[-1] if eos_token_id is None else int(eos_token_id)
            r = r + [pad] * (int(max_new_tokens) - len(r))
            out[i, s0:] = r[:int(max_new_tokens)]
        return out

    def explain(self) -> List[dict]:
        """Per-specialization cost rows (prefill buckets + the decode step)
        captured at AOT compile — render with
        ``observability.format_cost_table``."""
        return list(self._specializations)

    def cache_bytes(self) -> int:
        """Device bytes held by the preallocated K/V cache."""
        return 2 * int(np.prod(self._shape)) * self._ck.dtype.itemsize
